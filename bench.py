"""Benchmark entry — prints ONE JSON line the driver records.

Runs a BERT/ERNIE-base-style pretraining step (the north-star workload,
BASELINE.md: ERNIE-base pretrain tokens/sec/chip) built with the paddle_tpu
static-graph API and executed as one jitted XLA computation on the available
device (real TPU chip under axon; CPU otherwise).

MFU accounting: 6 * params * tokens/sec vs chip peak (v5e bf16 ~197 TFLOPs,
fallback to measured-only on CPU).
"""
import json
import os
import sys
import time

import numpy as np


def build_bert_base(vocab=30522, seq=512, hidden=768, layers_n=12, heads=12,
                    batch=8, use_amp=True, use_ring=False):
    import paddle_tpu.static as static
    from paddle_tpu.static import layers, nets
    from paddle_tpu import amp

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        ids = layers.data("ids", [-1, seq], dtype="int64")
        pos = layers.data("pos", [-1, seq], dtype="int64")
        labels = layers.data("labels", [-1, seq, 1], dtype="int64")
        emb = layers.embedding(ids, size=[vocab, hidden])
        pemb = layers.embedding(pos, size=[seq, hidden])
        h = layers.elementwise_add(emb, pemb)
        h = layers.layer_norm(h, begin_norm_axis=2)
        for _ in range(layers_n):
            # self-attention (use_ring: the ring_attention op — sequence
            # shards over an "sp" mesh axis under CompiledProgram, plain
            # attention on one device; the long-seq path's kernel)
            q = layers.fc(h, hidden, num_flatten_dims=2)
            k = layers.fc(h, hidden, num_flatten_dims=2)
            v = layers.fc(h, hidden, num_flatten_dims=2)
            ctx = nets.scaled_dot_product_attention(
                q, k, v, num_heads=heads, sequence_parallel=use_ring)
            attn_out = layers.fc(ctx, hidden, num_flatten_dims=2)
            h = layers.layer_norm(layers.elementwise_add(h, attn_out),
                                  begin_norm_axis=2)
            # ffn
            ffn = layers.fc(h, hidden * 4, num_flatten_dims=2, act="gelu")
            ffn = layers.fc(ffn, hidden, num_flatten_dims=2)
            h = layers.layer_norm(layers.elementwise_add(h, ffn),
                                  begin_norm_axis=2)
        logits = layers.fc(h, vocab, num_flatten_dims=2)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, labels))
        opt = static.Adam(learning_rate=1e-4)
        if use_amp:
            # bf16 compute on the MXU, fp32 master weights; bf16 shares
            # fp32's exponent range so no dynamic loss scaling is needed
            opt = amp.decorate(opt, init_loss_scaling=1.0,
                               use_dynamic_loss_scaling=False,
                               dest_dtype="bfloat16")
        opt.minimize(loss)
    return main, startup, loss


_FALLBACK_NOTE = ""


def _last_known_tpu_metric():
    """The last-good ON-CHIP headline from prior artifacts (BENCH_r*.json
    driver captures and perf_r*/ builder captures).  A CPU-fallback round
    carries this forward instead of silently overwriting the perf record
    with a tunnel hang (VERDICT r5 weak-point 7): the official record
    stays an under-statement of the chip, never an erasure of it."""
    import glob
    here = os.path.dirname(os.path.abspath(__file__))
    candidates = []

    def consider(src, d):
        if not isinstance(d, dict):
            return
        if d.get("metric") != "bert_base_pretrain_tokens_per_sec_per_chip":
            return
        entry = {"source": os.path.relpath(src, here),
                 "value": d.get("value"),
                 "unit": d.get("unit", "tokens/s/chip"),
                 "vs_baseline": d.get("vs_baseline", 0.0)}
        if "mfu" in d:
            entry["mfu"] = d["mfu"]
        candidates.append(entry)

    for p in sorted(glob.glob(os.path.join(here, "BENCH_r*.json"))):
        try:
            with open(p) as f:
                consider(p, json.load(f).get("parsed"))
        except (OSError, ValueError, AttributeError):
            continue  # unreadable, non-JSON, or top level not an object
    for p in sorted(glob.glob(os.path.join(here, "perf_r*", "*.json"))):
        try:
            with open(p) as f:
                consider(p, json.load(f))
        except (OSError, ValueError):
            continue
    if not candidates:
        return None
    # LAST known, not best-ever: an on-chip regression recorded in a
    # newer round must not be papered over by an older, higher number
    import re as _re

    def _round(c):
        m = _re.search(r"(?:BENCH_r|perf_r)0*(\d+)", c["source"])
        return int(m.group(1)) if m else -1

    newest = max(_round(c) for c in candidates)
    pool = [c for c in candidates if _round(c) == newest]
    return max(pool, key=lambda c: (c.get("vs_baseline") or 0.0,
                                    c.get("value") or 0.0))


def checkpoint_main():
    """Checkpoint-overhead A/B (`python bench.py --checkpoint` or
    BENCH_MODE=checkpoint): steady-state bert-tiny training throughput
    with (a) no checkpointing, (b) async CheckpointManager saves every
    step, (c) synchronous saves every step.  The async number must sit
    within a few percent of baseline — that's the whole point of
    decoupling snapshot from persistence — while sync pays the full
    serialize+fsync cost on the train path.  Prints ONE JSON line;
    numbers quoted in docs/checkpoint.md."""
    import tempfile
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"))
    import perf_smoke
    import paddle_tpu.static as static
    from paddle_tpu.checkpoint import CheckpointManager

    steps = int(os.environ.get("BENCH_CKPT_STEPS", 60))
    every = int(os.environ.get("BENCH_CKPT_EVERY", 10))
    reps = int(os.environ.get("BENCH_CKPT_REPS", 2))
    batch, seq, vocab = 8, 64, 2048
    rng = np.random.RandomState(0)
    idt = np.int64 if jax.config.jax_enable_x64 else np.int32

    def measure(mode):
        from paddle_tpu.core.program import _reset_unique_names
        _reset_unique_names()
        main_p, startup_p, loss, _ = perf_smoke.build_bert_tiny(
            vocab=vocab, seq=seq, hidden=128, layers_n=2, heads=4)
        exe = static.Executor()
        scope = static.Scope()
        feed = {"ids": rng.randint(0, vocab, (batch, seq)).astype(idt),
                "labels": rng.randint(0, vocab,
                                      (batch, seq, 1)).astype(idt)}
        mgr = None
        root = None
        try:
            with static.scope_guard(scope):
                exe.run(startup_p)
                exe.run(main_p, feed=feed, fetch_list=[loss])  # warm/compile
                if mode == "async":
                    root = tempfile.mkdtemp(prefix=f"bench_ckpt_{mode}_")
                    mgr = CheckpointManager(root, keep_last_n=3,
                                            max_in_flight=1)
                    exe.enable_checkpointing(mgr, program=main_p,
                                             every_n_steps=every,
                                             scope=scope)
                if mode == "sync":
                    root = tempfile.mkdtemp(prefix=f"bench_ckpt_{mode}_")
                    mgr = CheckpointManager(root, keep_last_n=3)
                t0 = time.time()
                for i in range(steps):
                    out = exe.run(main_p, feed=feed, fetch_list=[loss])
                    if mode == "sync" and (i + 1) % every == 0:
                        s, state, extra = exe.checkpoint_snapshot(
                            main_p, scope)
                        mgr.save(s, state, extra=extra, sync=True)
                np.asarray(out[0])
                dt = time.time() - t0
                if mgr is not None:
                    mgr.wait()
                    mgr.close()
        finally:
            if root is not None:
                import shutil
                shutil.rmtree(root, ignore_errors=True)
        return steps * batch * seq / dt

    # best-of-N per mode: CPU CI boxes swing 20%+ run-to-run, and the A/B
    # claim is about the checkpoint path, not scheduler noise
    base = max(measure("off") for _ in range(reps))
    async_tps = max(measure("async") for _ in range(reps))
    sync_tps = max(measure("sync") for _ in range(reps))
    result = {
        "metric": "ckpt_async_overhead_pct",
        "value": round((base / async_tps - 1.0) * 100, 2),
        "unit": "%",
        "steps": steps,
        "save_every_n_steps": every,
        "tokens_per_sec": {"off": round(base, 1),
                           "async": round(async_tps, 1),
                           "sync": round(sync_tps, 1)},
        "sync_overhead_pct": round((base / sync_tps - 1.0) * 100, 2),
    }
    print(json.dumps(result))


def elastic_main():
    """Elastic-schedule A/B (`python bench.py --elastic` or
    BENCH_MODE=elastic): steady-state training throughput of the plain
    data-parallel step vs the elasticized one (distributed/elastic.py) on
    the full local mesh.  The elastic path swaps psum gradient reduction
    for the world-size-invariant ordered fold (all_gather + explicit
    left-fold continuation) plus the masked commit — topology-invariant
    bitwise resume is bought with extra gradient wire volume and the fold
    chain, and this mode prices it.  Also re-runs two global steps on a
    half-size mesh and reports whether the committed loss matched the
    full-mesh value bitwise (the elastic contract, continuously
    verified).  Prints ONE JSON line."""
    import tempfile
    import jax
    if os.environ.get("BENCH_FORCE_CPU") or not os.environ.get(
            "BENCH_ELASTIC_TPU"):
        jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.static as static
    from paddle_tpu.core.program import _reset_unique_names
    from paddle_tpu.distributed.compiled_program import CompiledProgram
    from paddle_tpu.distributed.elastic import elasticize, rebucket_feeds
    from paddle_tpu.static import layers

    steps = int(os.environ.get("BENCH_ELASTIC_STEPS", 40))
    world = len(jax.devices())
    logical = 1 << (world.bit_length() - 1)  # pow2 floor
    batch_per_rank = int(os.environ.get("BENCH_ELASTIC_BATCH", 4))
    hidden = int(os.environ.get("BENCH_ELASTIC_HIDDEN", 256))
    rng = np.random.RandomState(0)
    gb = logical * batch_per_rank
    feeds = [{"x": rng.rand(gb, hidden).astype(np.float32),
              "y": rng.rand(gb, 1).astype(np.float32)}
             for _ in range(steps)]

    def build(elastic):
        _reset_unique_names()
        main_p, startup_p = static.Program(), static.Program()
        with static.program_guard(main_p, startup_p):
            x = layers.data("x", [-1, hidden])
            y = layers.data("y", [-1, 1])
            h = layers.fc(x, hidden, act="relu")
            h = layers.fc(h, hidden, act="relu")
            pred = layers.fc(h, 1)
            loss = layers.mean(
                layers.square(layers.elementwise_sub(pred, y)))
            static.Adam(learning_rate=1e-3).minimize(loss)
        meta = None
        if elastic:
            meta = elasticize(main_p, startup_p, logical_dp=logical,
                              loss_name=loss)
        return main_p, startup_p, loss, meta

    def measure(elastic, run_world, n_steps, warm=2):
        warm = min(warm, max(0, n_steps - 1))
        main_p, startup_p, loss, meta = build(elastic)
        cp = CompiledProgram(main_p).with_data_parallel(
            loss_name=loss.name,
            places=list(jax.devices())[:run_world])
        fetch = meta["loss_avg"] if elastic else loss
        exe = static.Executor()
        scope = static.Scope()
        losses = []
        t0 = time.time()
        with static.scope_guard(scope):
            exe.run(startup_p)
            for i, f in enumerate(feeds[:n_steps]):
                if i == warm:
                    t0 = time.time()
                for mf in rebucket_feeds(f, logical, run_world):
                    out = exe.run(cp, feed=mf, fetch_list=[fetch])
                losses.append(np.asarray(out[0]))
        dt = max(1e-9, time.time() - t0)
        return (n_steps - warm) * gb / dt, losses

    # A/B on `logical` devices, not `world`: a non-power-of-two device
    # count would not divide the schedule (the pow2 floor is the mesh)
    plain_tps, _ = measure(False, logical, steps)
    elastic_tps, ref_losses = measure(True, logical, steps)
    # contract check: two global steps on a half-size mesh, same math
    _, half_losses = measure(True, max(1, logical // 2), 4)
    bitwise = all(np.array_equal(a, b)
                  for a, b in zip(ref_losses[:4], half_losses))
    result = {
        "metric": "elastic_overhead_pct",
        "value": round((plain_tps / elastic_tps - 1.0) * 100, 2),
        "unit": "%",
        "steps": steps,
        "logical_dp": logical,
        "rows_per_sec": {"plain_dp": round(plain_tps, 1),
                         "elastic": round(elastic_tps, 1)},
        "half_mesh_loss_bitwise": bool(bitwise),
    }
    print(json.dumps(result))


def serving_main():
    """Serving benchmark mode (`python bench.py --serving` or
    BENCH_MODE=serving): N concurrent clients hammer the HTTP server's
    /predict on a tiny saved model and the steady-state QPS + p99 is
    measured twice — dynamic batching ON vs the serial-lock baseline —
    so the coalescing win is a number, not a claim.  Prints ONE JSON
    line like the training mode."""
    import tempfile
    if os.environ.get("BENCH_FORCE_CPU"):
        import jax
        jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"))
    import serve_smoke
    from paddle_tpu.inference.server import InferenceServer
    from paddle_tpu.serving.metrics import reset_serving_stats

    clients = int(os.environ.get("BENCH_SERVING_CLIENTS", 8))
    requests = int(os.environ.get("BENCH_SERVING_REQUESTS", 25))
    max_batch = int(os.environ.get("BENCH_SERVING_MAX_BATCH", 8))
    # ~1ms fill window measured best on CPU: requests pile up naturally
    # while the device runs, so a long stall only adds latency
    wait_ms = float(os.environ.get("BENCH_SERVING_WAIT_MS", 1.0))
    model_dir = tempfile.mkdtemp(prefix="bench_serving_")
    # weights-streaming-bound mlp (2048 hidden x 8 layers): a batch-8 run
    # streams the same 128MB of weights as batch-1, so coalescing is
    # near-free — the serving regime batching exists for (on the TPU the
    # same holds for MXU occupancy at small batch)
    xb, ref, out_name = serve_smoke.save_tiny_model(
        model_dir, in_dim=256, classes=8, hidden=2048, depth=8)
    payloads = [{"inputs": {"x": xb[j:j + 1].tolist()}}
                for j in range(xb.shape[0])]

    def measure(batching):
        reset_serving_stats()
        srv = InferenceServer(model_dir, batching=batching,
                              max_batch=max_batch, max_wait_ms=wait_ms,
                              max_queue=max(64, clients * 4))
        srv.start()
        try:
            base = f"http://{srv.host}:{srv.port}"
            b = 1
            while b <= max_batch:  # warm every pow2 bucket
                serve_smoke.http_json(
                    base + "/predict",
                    {"inputs": {"x": np.repeat(xb[:1], b, 0).tolist()}})
                b <<= 1
            # untimed pre-load: absorbs process-global first-dispatch
            # costs so neither phase's number depends on phase ORDER
            serve_smoke.run_load(base, payloads, clients,
                                 max(3, requests // 5))
            warm_traces = serve_smoke.http_json(base + "/stats")[
                "predictor_cache"]["traces"]
            reset_serving_stats()  # latency percentiles: steady only
            dt = serve_smoke.run_load(base, payloads, clients, requests)
            stats = serve_smoke.http_json(base + "/stats")
        finally:
            srv.stop()
        s = stats["serving"]
        lat = s.get("serving.latency_ms", {})
        return {
            "qps": round(clients * requests / dt, 2),
            "p50_ms": round(lat.get("p50", 0.0), 3),
            "p99_ms": round(lat.get("p99", 0.0), 3),
            "coalesced": s.get("serving.batch.coalesced", 0),
            "batch_runs": s.get("serving.batch.runs", 0),
            "traces_after_warmup":
                stats["predictor_cache"]["traces"] - warm_traces,
        }

    batched = measure(batching=True)
    serial = measure(batching=False)
    result = {
        "metric": "serving_steady_qps",
        "value": batched["qps"],
        "unit": "req/s",
        "clients": clients,
        "requests_per_client": requests,
        "p50_ms": batched["p50_ms"],
        "p99_ms": batched["p99_ms"],
        "coalesced_batches": batched["coalesced"],
        "batch_runs": batched["batch_runs"],
        "traces_after_warmup": batched["traces_after_warmup"],
        "serial_baseline_qps": serial["qps"],
        "serial_p99_ms": serial["p99_ms"],
        "speedup_vs_serial": round(batched["qps"] /
                                   max(serial["qps"], 1e-9), 3),
        "paged_kv": _serving_paged_ab(),
        "radix_prefix": _serving_radix_ab(),
        "speculative": _serving_speculative_ab(),
        "tp_decode": _serving_tp_decode_ab(),
        "int8_paged": _serving_int8_ab(),
    }
    print(json.dumps(result))


def _serving_paged_ab():
    """Paged-vs-fixed-slot generation A/B at EQUAL KV HBM: the planner
    (`static.page_budget`, the HBM-walker sizing path) chooses the page
    budget; the fixed-slot baseline gets the SAME kv byte budget spent
    as dense worst-case max-context slots (generously uncharged for
    workspace, biasing the comparison AGAINST paging).  Both engines
    drain an identical shared-system-prompt workload; reported are peak
    concurrent sequences (the capacity claim), QPS/chip, p50/p95/p99,
    page-occupancy/sharing stats, and token-equality vs per-sequence
    generate()."""
    import threading
    import paddle_tpu.dygraph as dg
    from paddle_tpu.models import GPTConfig, GPTModel, GPTForGeneration
    from paddle_tpu.serving import ContinuousBatchingEngine, PagedKVPool
    from paddle_tpu.serving.metrics import (percentiles,
                                            reset_serving_stats)
    from paddle_tpu.static import page_budget
    import jax

    n_req = int(os.environ.get("BENCH_SERVING_GEN_REQUESTS", 24))
    kv_hbm = int(os.environ.get("BENCH_SERVING_GEN_HBM", 1 << 20))
    max_new = 8
    rng = np.random.RandomState(7)
    with dg.guard():
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=2, max_position=128, dropout=0.0)
        m = GPTForGeneration(GPTModel(cfg))
        m.eval()
        weight_bytes = int(sum(np.asarray(p.numpy()).nbytes
                               for p in m.gpt.parameters()))
        # planner-chosen budget: weights + the KV grant, never hand-set
        plan = page_budget(m, page_tokens=16, max_context=128,
                           hbm_bytes=weight_bytes + kv_hbm)
        token_bytes = plan["page_bytes"] // plan["page_tokens"]
        # fixed-slot capacity at the same kv budget: every slot commits
        # a dense max-context buffer up front
        fixed_slots = max(1, plan["kv_bytes"] //
                          (token_bytes * plan["max_context"]))
        # shared 16-token system prompt + unique 8-token user tail
        head = rng.randint(2, 64, (16,)).astype(np.int64)
        prompts = [np.concatenate([head,
                                   rng.randint(2, 64, (8,))
                                   .astype(np.int64)])
                   for _ in range(n_req)]
        refs = [np.asarray(m.generate(p[None], max_length=max_new,
                                      decode_strategy="greedy_search")[0])
                for p in prompts[:3]]

        def drain(eng, pool=None):
            reset_serving_stats()
            peak = {"slots": 0, "pages": 0}
            done = threading.Event()

            def poll():
                while not done.is_set():
                    peak["slots"] = max(peak["slots"], eng.active_slots)
                    if pool is not None:
                        peak["pages"] = max(
                            peak["pages"],
                            pool.num_pages - pool.pages_free)
                    time.sleep(0.001)

            eng.start()
            t = threading.Thread(target=poll, daemon=True)
            t.start()
            t0 = time.time()
            try:
                futs = [eng.submit(p, max_length=max_new)
                        for p in prompts]
                outs = [np.asarray(f.result(timeout=300)) for f in futs]
            finally:
                done.set()
                eng.stop()
            dt = time.time() - t0
            t.join(timeout=1.0)
            lat = percentiles()
            return outs, dt, peak, lat

        pool = PagedKVPool.from_plan(plan)
        paged_eng = ContinuousBatchingEngine(m, max_slots=n_req,
                                             kv_pool=pool)
        p_outs, p_dt, p_peak, p_lat = drain(paged_eng, pool)
        pool_stats = pool.stats()
        pool.assert_drained()
        fixed_eng = ContinuousBatchingEngine(m, max_slots=fixed_slots)
        f_outs, f_dt, f_peak, f_lat = drain(fixed_eng)

    token_equal = all(
        np.array_equal(p_outs[i], refs[i]) for i in range(len(refs))
    ) and all(np.array_equal(f_outs[i], p_outs[i])
              for i in range(len(p_outs)))
    chips = max(1, jax.device_count())

    def _side(outs, dt, peak, lat):
        return {
            "qps": round(len(outs) / dt, 2),
            "qps_per_chip": round(len(outs) / dt / chips, 2),
            "tokens_per_s": round(len(outs) * max_new / dt, 1),
            "wall_s": round(dt, 2),
            "peak_concurrent_seqs": peak["slots"],
            "p50_ms": round(lat.get("p50", 0.0), 3),
            "p95_ms": round(lat.get("p95", 0.0), 3),
            "p99_ms": round(lat.get("p99", 0.0), 3),
        }

    paged_side = _side(p_outs, p_dt, p_peak, p_lat)
    paged_side["peak_pages_used"] = p_peak["pages"]
    paged_side["page_occupancy_peak"] = round(
        p_peak["pages"] / max(1, plan["pages"]), 4)
    fixed_side = _side(f_outs, f_dt, f_peak, f_lat)
    return {
        "requests": n_req,
        "max_new_tokens": max_new,
        "kv_budget_bytes": plan["kv_bytes"],
        "plan": {k: plan[k] for k in
                 ("pages", "page_tokens", "max_slots", "max_context",
                  "kv_bytes", "workspace_bytes", "source")},
        "fixed_slots_at_equal_hbm": fixed_slots,
        "paged": paged_side,
        "fixed": fixed_side,
        "pool": pool_stats,
        "capacity_ratio": round(
            paged_side["peak_concurrent_seqs"] /
            max(1, fixed_side["peak_concurrent_seqs"]), 2),
        "token_equal_vs_generate": bool(token_equal),
    }


def _serving_radix_ab():
    """Retained-prefix generation A/B on a repeated-system-prompt
    trace: a few long system prompts recur across the request stream
    with unique user tails, so after each head's first retirement the
    radix tree serves its pages back and prefill runs only the
    uncovered suffix.  The cold side is an identical engine with no
    prefix cache.  Requests drain sequentially (each retires before the
    next prefills) so the hit pattern is the trace's, not a scheduling
    race's.  Reported are the retained-hit rate, prefill tokens skipped
    vs actually run, tokens/s on both sides, and token-equality — a
    radix hit must never change output."""
    import paddle_tpu.dygraph as dg
    from paddle_tpu.models import GPTConfig, GPTModel, GPTForGeneration
    from paddle_tpu.serving import (ContinuousBatchingEngine,
                                    PagedKVPool, RadixPrefixCache,
                                    metrics)
    from paddle_tpu.serving.metrics import reset_serving_stats
    from paddle_tpu.static import page_budget

    n_req = int(os.environ.get("BENCH_SERVING_RADIX_REQUESTS", 24))
    kv_hbm = int(os.environ.get("BENCH_SERVING_GEN_HBM", 1 << 20))
    n_heads, head_tokens, max_new = 3, 32, 8
    rng = np.random.RandomState(17)
    with dg.guard():
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=2, max_position=128, dropout=0.0)
        m = GPTForGeneration(GPTModel(cfg))
        m.eval()
        weight_bytes = int(sum(np.asarray(p.numpy()).nbytes
                               for p in m.gpt.parameters()))
        plan = page_budget(m, page_tokens=16, max_context=128,
                           hbm_bytes=weight_bytes + kv_hbm)
        heads = [rng.randint(2, 64, (head_tokens,)).astype(np.int64)
                 for _ in range(n_heads)]
        prompts = [np.concatenate([heads[i % n_heads],
                                   rng.randint(2, 64, (8,))
                                   .astype(np.int64)])
                   for i in range(n_req)]

        def drain_seq(eng):
            reset_serving_stats()
            eng.start()
            t0 = time.time()
            try:
                outs = [np.asarray(eng.submit(p, max_length=max_new)
                                   .result(timeout=300))
                        for p in prompts]
            finally:
                eng.stop()
            return outs, time.time() - t0

        cold_pool = PagedKVPool.from_plan(plan)
        c_outs, c_dt = drain_seq(
            ContinuousBatchingEngine(m, max_slots=4, kv_pool=cold_pool))
        c_prefill = metrics.counter("gen.prefill_tokens")
        cold_pool.assert_drained()

        pool = PagedKVPool.from_plan(plan)
        radix = RadixPrefixCache.from_plan(pool)
        w_outs, w_dt = drain_seq(
            ContinuousBatchingEngine(m, max_slots=4, kv_pool=pool,
                                     prefix_cache=radix))
        w_prefill = metrics.counter("gen.prefill_tokens")
        hit_tokens = metrics.counter("kv.radix_hit_tokens")
        retained = pool.pages_retained
        pool.assert_drained()
        radix.clear()
        pool.assert_drained()

    token_equal = all(np.array_equal(a, b)
                      for a, b in zip(w_outs, c_outs))
    return {
        "requests": n_req,
        "distinct_heads": n_heads,
        "head_tokens": head_tokens,
        "watermarks": [radix.low_watermark, radix.high_watermark],
        "radix_hits": radix.hits,
        "hit_rate": round(radix.hits / max(1, n_req), 3),
        "prefill_tokens_skipped": int(hit_tokens),
        "prefill_tokens_cold": int(c_prefill),
        "prefill_tokens_warm": int(w_prefill),
        "retained_pages_at_drain": int(retained),
        "evicted_pages": radix.evicted_pages,
        "tokens_per_s_warm": round(n_req * max_new / w_dt, 1),
        "tokens_per_s_cold": round(n_req * max_new / c_dt, 1),
        "speedup_vs_cold": round(c_dt / max(w_dt, 1e-9), 3),
        "token_equal_vs_cold": bool(token_equal),
    }


def _serving_speculative_ab():
    """Speculative-decode generation A/B: a 2-layer stamped sibling
    proposes k tokens per slot and the target verifies the whole batch
    in one step; the plain side is the same paged engine with no draft.
    The stamp here is full-depth (the target IS 2 layers) so acceptance
    is total and accepted-tokens/step approaches 1 + k — the machinery
    ceiling; production drafts are shallower and land in between.  Both
    sides drain the same concurrent greedy workload; reported are
    accepted/step, proposal/rollback totals, wall-clock on both sides,
    and token-equality — rejection sampling must be invisible in
    output."""
    import paddle_tpu.dygraph as dg
    from paddle_tpu.models import GPTConfig, GPTModel, GPTForGeneration
    from paddle_tpu.serving import (ContinuousBatchingEngine,
                                    PagedKVPool, SpeculativeDecoder,
                                    metrics, stamp_draft)
    from paddle_tpu.serving.metrics import reset_serving_stats
    from paddle_tpu.static import page_budget

    n_req = int(os.environ.get("BENCH_SERVING_SPEC_REQUESTS", 8))
    kv_hbm = int(os.environ.get("BENCH_SERVING_GEN_HBM", 1 << 20))
    max_new, k = 16, 3
    rng = np.random.RandomState(19)
    with dg.guard():
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=2, max_position=128, dropout=0.0)
        m = GPTForGeneration(GPTModel(cfg))
        m.eval()
        weight_bytes = int(sum(np.asarray(p.numpy()).nbytes
                               for p in m.gpt.parameters()))
        plan = page_budget(m, page_tokens=16, max_context=128,
                           hbm_bytes=weight_bytes + kv_hbm,
                           draft_layers=2)
        prompts = [rng.randint(2, 64, (8 + (i % 4),)).astype(np.int64)
                   for i in range(n_req)]

        def drain(eng):
            reset_serving_stats()
            eng.start()
            t0 = time.time()
            try:
                futs = [eng.submit(p, max_length=max_new)
                        for p in prompts]
                outs = [np.asarray(f.result(timeout=300))
                        for f in futs]
            finally:
                eng.stop()
            return outs, time.time() - t0

        plain_pool = PagedKVPool.from_plan(plan)
        p_outs, p_dt = drain(
            ContinuousBatchingEngine(m, max_slots=4,
                                     kv_pool=plain_pool))
        plain_pool.assert_drained()

        spec = SpeculativeDecoder(stamp_draft(m, num_layers=2), k=k)
        pool = PagedKVPool.from_plan(plan)
        s_outs, s_dt = drain(
            ContinuousBatchingEngine(m, max_slots=4, kv_pool=pool,
                                     speculative=spec))
        steps = metrics.counter("spec.steps")
        proposed = metrics.counter("spec.proposed")
        accepted = metrics.counter("spec.accepted")
        rolled = metrics.counter("spec.rollback_cols")
        # per-ROW commit depth (the engine observes each row's committed
        # count every verify step) — gen.tokens / spec.steps would
        # conflate batch occupancy with speculation depth
        per_row = metrics.percentiles("spec.accepted_per_step")
        pool.assert_drained()

    token_equal = all(np.array_equal(a, b)
                      for a, b in zip(s_outs, p_outs))
    return {
        "requests": n_req,
        "max_new_tokens": max_new,
        "draft_layers": 2,
        "k": k,
        "draft_kv_bytes": plan["draft_kv_bytes"],
        "accepted_per_step": round(per_row.get("mean", 0.0), 2),
        "verify_steps": int(steps),
        "proposed": int(proposed),
        "accepted": int(accepted),
        "rollback_cols": int(rolled),
        "draft_tokens": int(spec.draft_tokens),
        "wall_s_spec": round(s_dt, 2),
        "wall_s_plain": round(p_dt, 2),
        "speedup_vs_plain": round(p_dt / max(s_dt, 1e-9), 3),
        "token_equal_vs_plain": bool(token_equal),
    }


def _serving_tp_decode_ab():
    """tp-sharded decode A/B at EQUAL per-chip HBM: the same model, the
    same pinned per-chip budget, page pools carved by
    `static.page_budget` at tp=1 and tp=2.  At tp=2 each chip holds
    half the Megatron-splittable weights and half of every KV byte
    (heads shard), so the per-chip budget carves more pages — reported
    as page capacity and peak concurrent sequences — while the decode
    itself runs `serving.TPShardedDecoder`'s CompiledProgram across the
    dp×mp mesh.  Both sides drain the same greedy workload;
    token-equality vs the tp=1 engine is ASSERTED (sharded math must be
    invisible in output), tokens/s measures what the mp collectives
    cost on this host."""
    import paddle_tpu.dygraph as dg
    from paddle_tpu.models import GPTConfig, GPTModel, GPTForGeneration
    from paddle_tpu.serving import ContinuousBatchingEngine, PagedKVPool
    from paddle_tpu.serving.metrics import reset_serving_stats
    from paddle_tpu.static import page_budget

    n_req = int(os.environ.get("BENCH_SERVING_TP_REQUESTS", 8))
    tp = int(os.environ.get("BENCH_SERVING_TP_DEGREE", 2))
    kv_hbm = int(os.environ.get("BENCH_SERVING_TP_HBM", 1 << 18))
    max_new = 8
    rng = np.random.RandomState(23)
    with dg.guard():
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, max_position=128, dropout=0.0)
        m = GPTForGeneration(GPTModel(cfg))
        m.eval()
        weight_bytes = int(sum(np.asarray(p.numpy()).nbytes
                               for p in m.gpt.parameters()))
        # the PINNED per-chip budget both sides must live inside —
        # weights + a thin KV grant, so the tp=1 pool is starved and
        # the tp=2 per-chip savings convert into pages
        hbm = weight_bytes + kv_hbm
        plan1 = page_budget(m, page_tokens=16, max_context=128,
                            hbm_bytes=hbm)
        plan2 = page_budget(m, page_tokens=16, max_context=128,
                            hbm_bytes=hbm, tp_degree=tp)
        prompts = [rng.randint(2, 64, (6 + (i % 5),)).astype(np.int64)
                   for i in range(n_req)]

        def drain(eng):
            reset_serving_stats()
            eng.start()
            t0 = time.time()
            try:
                futs = [eng.submit(p, max_length=max_new)
                        for p in prompts]
                outs = [np.asarray(f.result(timeout=600))
                        for f in futs]
            finally:
                eng.stop()
            return outs, time.time() - t0

        pool1 = PagedKVPool.from_plan(plan1)
        outs1, dt1 = drain(ContinuousBatchingEngine(
            m, max_slots=4, kv_pool=pool1))
        pool1.assert_drained()

        pool2 = PagedKVPool.from_plan(plan2)
        eng2 = ContinuousBatchingEngine(m, max_slots=4, kv_pool=pool2)
        outs2, dt2 = drain(eng2)
        pool2.assert_drained()

    # the tp A/B's contract: sharding must be invisible in output
    assert all(np.array_equal(a, b) for a, b in zip(outs1, outs2)), \
        "tp-sharded decode diverged from single-chip greedy"
    tok = n_req * max_new
    return {
        "requests": n_req,
        "max_new_tokens": max_new,
        "tp_degree": eng2.tp_degree,
        "hbm_per_chip_bytes": hbm,
        "pages_tp1": plan1["pages"],
        "pages_tp2": plan2["pages"],
        "page_capacity_ratio": round(plan2["pages"] /
                                     max(1, plan1["pages"]), 2),
        "max_slots_tp1": plan1["max_slots"],
        "max_slots_tp2": plan2["max_slots"],
        "tokens_per_s_tp1": round(tok / dt1, 1),
        "tokens_per_s_tp2": round(tok / dt2, 1),
        "wall_s_tp1": round(dt1, 2),
        "wall_s_tp2": round(dt2, 2),
        "token_equal": True,
    }


def _serving_int8_ab():
    """int8-vs-fp32 generation A/B at EQUAL per-chip HBM: the same
    model, the same pinned budget (weights + a thin KV grant), pools
    carved by `static.page_budget` at fp32 and at
    kv_dtype/weight_dtype="int8".  int8 KV pages store half the bytes
    (plus the fp32 scale sidecar, which the planner charges) and int8
    weights return 3 of every 4 weight bytes to the carve, so the int8
    side holds ~2-4x the pages and concurrent sequences — the capacity
    claim is ASSERTED at >= 1.9x, and so is token-equality: on this
    model the per-channel weight grid plus per-page KV scales leave
    greedy argmax unchanged (the tested contract; see docs/serving.md
    for the tolerance rule if a future model breaks it).  tokens/s on
    both sides measures what dynamic activation quant costs on a host
    CPU where int8 has no MXU to win back — the 2x rate claim is the
    queued on-chip row, not this number."""
    import threading
    import paddle_tpu.dygraph as dg
    from paddle_tpu.models import GPTConfig, GPTModel, GPTForGeneration
    from paddle_tpu.serving import ContinuousBatchingEngine, PagedKVPool
    from paddle_tpu.serving.metrics import reset_serving_stats
    from paddle_tpu.static import page_budget

    n_req = int(os.environ.get("BENCH_SERVING_INT8_REQUESTS", 16))
    tp = int(os.environ.get("BENCH_SERVING_INT8_TP", 1))
    kv_hbm = int(os.environ.get("BENCH_SERVING_INT8_HBM", 1 << 18))
    max_new = 8
    rng = np.random.RandomState(29)
    with dg.guard():
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, max_position=128, dropout=0.0)
        m = GPTForGeneration(GPTModel(cfg))
        m.eval()
        weight_bytes = int(sum(np.asarray(p.numpy()).nbytes
                               for p in m.gpt.parameters()))
        # the PINNED per-chip budget both sides must live inside
        hbm = weight_bytes + kv_hbm
        plan_f = page_budget(m, page_tokens=16, max_context=128,
                             hbm_bytes=hbm, tp_degree=tp)
        plan_i = page_budget(m, page_tokens=16, max_context=128,
                             hbm_bytes=hbm, tp_degree=tp,
                             kv_dtype="int8", weight_dtype="int8")
        prompts = [rng.randint(2, 64, (6 + (i % 5),)).astype(np.int64)
                   for i in range(n_req)]

        def drain(eng, pool):
            reset_serving_stats()
            peak = {"slots": 0, "pages": 0}
            done = threading.Event()

            def poll():
                while not done.is_set():
                    peak["slots"] = max(peak["slots"], eng.active_slots)
                    peak["pages"] = max(peak["pages"],
                                        pool.num_pages - pool.pages_free)
                    time.sleep(0.001)

            eng.start()
            t = threading.Thread(target=poll, daemon=True)
            t.start()
            t0 = time.time()
            try:
                futs = [eng.submit(p, max_length=max_new)
                        for p in prompts]
                outs = [np.asarray(f.result(timeout=600))
                        for f in futs]
            finally:
                done.set()
                eng.stop()
            dt = time.time() - t0
            t.join(timeout=1.0)
            return outs, dt, peak

        pool_f = PagedKVPool.from_plan(plan_f)
        f_outs, f_dt, f_peak = drain(ContinuousBatchingEngine(
            m, max_slots=n_req, kv_pool=pool_f), pool_f)
        pool_f.assert_drained()

        pool_i = PagedKVPool.from_plan(plan_i)
        eng_i = ContinuousBatchingEngine(m, max_slots=n_req,
                                         kv_pool=pool_i)
        i_outs, i_dt, i_peak = drain(eng_i, pool_i)
        i_stats = pool_i.stats()
        pool_i.assert_drained()

    # the int8 A/B's two contracts
    page_ratio = plan_i["pages"] / max(1, plan_f["pages"])
    assert page_ratio >= 1.9, \
        f"int8 carve only {page_ratio:.2f}x fp32 pages at equal HBM"
    assert all(np.array_equal(a, b) for a, b in zip(f_outs, i_outs)), \
        "int8 decode diverged from fp32 greedy"
    tok = n_req * max_new
    return {
        "requests": n_req,
        "max_new_tokens": max_new,
        "tp_degree": tp,
        "hbm_per_chip_bytes": hbm,
        "kv_dtype": i_stats["kv_dtype"],
        "weight_dtype": eng_i.weight_dtype,
        "pages_fp32": plan_f["pages"],
        "pages_int8": plan_i["pages"],
        "page_capacity_ratio": round(page_ratio, 2),
        "peak_concurrent_seqs_fp32": f_peak["slots"],
        "peak_concurrent_seqs_int8": i_peak["slots"],
        "peak_pages_used_int8": i_peak["pages"],
        "quant_scale_clips": i_stats["quant_scale_clips"],
        "tokens_per_s_fp32": round(tok / f_dt, 1),
        "tokens_per_s_int8": round(tok / i_dt, 1),
        "wall_s_fp32": round(f_dt, 2),
        "wall_s_int8": round(i_dt, 2),
        "token_equal": True,
    }


def _argv_value(flag):
    """Optional value following `flag` in argv (None when the flag is
    absent, "" when it is last or followed by another --option)."""
    if flag not in sys.argv:
        return None
    i = sys.argv.index(flag)
    if i + 1 < len(sys.argv) and not sys.argv[i + 1].startswith("--"):
        return sys.argv[i + 1]
    return ""


def _bench_knobs():
    """Shared --remat / --grad-merge / --ring knob parsing (argv wins
    over env).  Returns (remat_mode, grad_merge_k, use_ring) where
    remat_mode is "" / "always" / "auto".  Both `--remat` and
    `--remat auto` work, matching the BENCH_REMAT=1|auto spellings."""
    remat = _argv_value("--remat")
    if remat is None:
        remat = os.environ.get("BENCH_REMAT", "")
    elif remat == "":
        remat = os.environ.get("BENCH_REMAT", "") or "1"
    if remat in ("0", "false"):
        remat = ""
    remat_mode = "" if not remat else ("auto" if remat == "auto"
                                       else "always")
    gm_raw = _argv_value("--grad-merge")
    if gm_raw is None or gm_raw == "":
        if gm_raw == "":
            raise SystemExit("bench: --grad-merge needs a step count "
                             "(e.g. --grad-merge 2)")
        gm_raw = os.environ.get("BENCH_GRAD_MERGE", "0")
    gm = int(gm_raw or 0)
    ring = os.environ.get("BENCH_RING", "") not in ("", "0", "false") \
        or "--ring" in sys.argv
    return remat_mode, gm, ring


def _dp_shard_knob():
    """--dp-shard [N] / BENCH_DP_SHARD=N: ZeRO optimizer-state sharding
    A/B (distributed/sharding.py).  A bare --dp-shard targets the
    v5e-32 pod slice's 8-chip host world."""
    raw = _argv_value("--dp-shard")
    if raw is None:
        raw = os.environ.get("BENCH_DP_SHARD", "0")
    elif raw == "":
        raw = os.environ.get("BENCH_DP_SHARD", "") or "8"
    ds = int(raw or 0)
    if ds < 0:
        raise SystemExit("bench: --dp-shard needs a non-negative world "
                         "size (e.g. --dp-shard 8)")
    return ds


def _zero_stage_knob():
    """--zero-stage S / BENCH_ZERO_STAGE=S: which ZeRO stage the
    --dp-shard rewrite applies (1 = optimizer slots, 2 = + sharded
    gradient accumulation under --grad-merge, 3 = full parameter
    sharding with JIT gathers).  Default 1; ignored without a dp_shard
    world."""
    raw = _argv_value("--zero-stage")
    if raw is None or raw == "":
        raw = os.environ.get("BENCH_ZERO_STAGE", "1")
    zs = int(raw or 1)
    if zs == 0:
        return 1  # 0 = "unset", mirroring BENCH_DP_SHARD=0 (ignored
        # anyway without a dp_shard world)
    if zs not in (1, 2, 3):
        raise SystemExit("bench: --zero-stage must be 1, 2 or 3")
    return zs


def _tp_knob():
    """--tp [N] / BENCH_TP_DEGREE=N: Megatron tensor-parallel A/B — the
    model builds through the tensor_parallel builders at degree N
    (models.build_transformer_lm).  On this bench's single-device
    Executor path the Megatron collectives degrade to identity, so
    tokens/s measures the tp build's dispatch/fusion overhead while
    predicted_peak_bytes (walker tp division) and wire_bytes_per_axis
    (mp ring at its own degree, batch-bound) report the dp×tp mesh
    story — the mesh numbers need CompiledProgram over real chips
    (queued as tp2_*/auto_tp_* in perf_r05/queue.txt).  A bare --tp
    targets degree 2 (the v5e 4×2 host split)."""
    raw = _argv_value("--tp")
    if raw is None:
        raw = os.environ.get("BENCH_TP_DEGREE", "0")
    elif raw == "":
        raw = os.environ.get("BENCH_TP_DEGREE", "") or "2"
    tp = int(raw or 0)
    if tp < 0:
        raise SystemExit("bench: --tp needs a non-negative degree "
                         "(e.g. --tp 2)")
    return 0 if tp == 1 else tp


def seq_ladder_main():
    """Sequence-length ladder (`python bench.py --seq-ladder` or
    BENCH_MODE=seq_ladder): builds the bench model at each rung —
    optionally with remat (BENCH_REMAT=1/auto) and/or ring attention
    (BENCH_RING=1) — and emits the HBM estimator's PREDICTED peak
    alongside measured tokens/s, one JSON line with the whole ladder.
    On chip, rungs the estimator predicts to OOM are SKIPPED instead of
    burning tunnel minutes on an allocator error; on CPU the rungs
    shrink so the mode runs end-to-end in CI.  Token budget per rung is
    constant (BENCH_LADDER_TOKENS) so batch = tokens/seq, matching the
    r5 ladder protocol (perf_r05/ladder.log)."""
    import jax
    if os.environ.get("BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")
    else:
        ok, reason = _probe_tpu()
        if not ok:
            sys.stderr.write(f"bench: seq-ladder on CPU ({reason})\n")
            jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.static as static
    from paddle_tpu.core.flags import set_flags
    from paddle_tpu.core.program import _reset_unique_names

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    default_ladder = "512,1024,2048,4096" if on_tpu else "64,128"
    seqs = [int(s) for s in os.environ.get(
        "BENCH_SEQ_LADDER", default_ladder).split(",") if s]
    # estimator-only extension rungs: planned (knobs chosen by
    # static.plan_program) and verdicted but NEVER executed — the
    # long-context regime where even one step would burn tunnel time
    default_est = "8192,16384,32768" if on_tpu else "256"
    est_seqs = [int(s) for s in os.environ.get(
        "BENCH_SEQ_LADDER_EST", default_est).split(",") if s]
    tokens = int(os.environ.get("BENCH_LADDER_TOKENS",
                                32768 if on_tpu else 512))
    layers_n = int(os.environ.get("BENCH_LAYERS", 12 if on_tpu else 2))
    hidden = int(os.environ.get("BENCH_HIDDEN", 768 if on_tpu else 128))
    heads = int(os.environ.get("BENCH_HEADS", 12 if on_tpu else 4))
    vocab = int(os.environ.get("BENCH_VOCAB", 30522 if on_tpu else 1024))
    steps = int(os.environ.get("BENCH_STEPS", 10 if on_tpu else 5))
    use_amp = os.environ.get("BENCH_NO_AMP", "") in ("", "0", "false")
    remat_mode, _, use_ring = _bench_knobs()

    rng = np.random.RandomState(0)
    rows = []
    for seq in seqs:
        batch = max(1, tokens // seq)
        _reset_unique_names()
        if remat_mode:
            set_flags({"recompute": remat_mode, "hbm_assume_batch": batch})
        try:
            main_p, startup_p, loss = build_bert_base(
                vocab, seq, hidden, layers_n, heads, batch,
                use_amp=use_amp, use_ring=use_ring)
        finally:
            set_flags({"recompute": "", "hbm_assume_batch": 0})
        mem = static.analyze_program(main_p, batch=batch)
        row = {"seq": seq, "batch": batch,
               "predicted_peak_bytes": mem["peak_bytes"],
               "predicted_peak_gib": round(mem["peak_bytes"] / 2 ** 30, 2),
               "predicted_fits": mem["fits"],
               "remat": remat_mode or "off", "ring": use_ring}
        if on_tpu and not mem["fits"]:
            # the whole point of compile-time accounting: a predicted
            # OOM costs zero tunnel seconds
            row["skipped"] = "predicted OOM at " + \
                f"{mem['budget_bytes'] / 2 ** 30:.2f} GiB budget"
            rows.append(row)
            continue
        idt = np.int64 if jax.config.jax_enable_x64 else np.int32
        feed = {
            "ids": rng.randint(0, vocab, (batch, seq)).astype(idt),
            "pos": np.tile(np.arange(seq), (batch, 1)).astype(idt),
            "labels": rng.randint(0, vocab, (batch, seq, 1)).astype(idt),
        }
        exe, scope = static.Executor(), static.Scope()
        with static.scope_guard(scope):
            exe.run(startup_p)
            exe.run(main_p, feed=feed, fetch_list=[loss])   # warm/compile
            exe.run(main_p, feed=feed, fetch_list=[])
            t0 = time.time()
            for _ in range(steps - 1):
                exe.run(main_p, feed=feed, fetch_list=[])
            out = exe.run(main_p, feed=feed, fetch_list=[loss])
            np.asarray(out[0])
            dt = time.time() - t0
        exe.close()
        row["tokens_per_sec"] = round(steps * batch * seq / dt, 2)
        rows.append(row)
    # -- estimator-only rungs: plan, verdict, never execute ----------------
    for seq in est_seqs:
        batch = max(1, tokens // seq)
        variants = {}

        def _build(ring):
            _reset_unique_names()
            return build_bert_base(vocab, seq, hidden, layers_n, heads,
                                   batch, use_amp=use_amp, use_ring=ring)
        main_p, startup_p, _ = _build(False)
        ring_main, ring_startup, _ = _build(True)
        variants["ring"] = (ring_main, ring_startup)
        # estimator sweep: many rungs x full lattice — remat/ring are
        # the long-seq knobs; verification is skipped for wall time
        # (plan_smoke + tests gate the verified path)
        plan = static.plan_program(
            main_p, startup_p, world=1, batch=batch, variants=variants,
            knobs={"grad_merge": (1,), "dp_shard": (0,)}, verify=False)
        rows.append({
            "seq": seq, "batch": batch,
            "estimator_only": True,
            "planned_knobs": dict(plan.knobs),
            "predicted_peak_bytes": plan.predicted_peak_bytes,
            "predicted_peak_gib":
                round(plan.predicted_peak_bytes / 2 ** 30, 2),
            "predicted_fits": plan.predicted_fits,
            "predicted_step_ms": round(plan.predicted_step_ms, 2),
        })
    measured = [r for r in rows if "tokens_per_sec" in r]
    result = {
        "metric": "seq_ladder_tokens_per_sec",
        "value": measured[-1]["tokens_per_sec"] if measured else 0.0,
        "unit": "tokens/s",
        "on_tpu": on_tpu,
        "remat": remat_mode or "off",
        "ring": use_ring,
        "hbm_budget_bytes": static.hbm_budget_bytes(),
        "ladder": rows,
    }
    if not on_tpu:
        result["failed"] = True
        result["note"] = "CPU run; predicted peaks are the deliverable"
    print(json.dumps(result))


def tp_main():
    """Tensor-parallel A/B (`python bench.py --tp N` or
    BENCH_TP_DEGREE=N): builds the bench geometry through the
    tensor_parallel builders (models.build_transformer_lm) and trains it
    over a dp×tp CompiledProgram mesh on the local devices — the tp
    shards need a real mesh (the per-head reshapes bake local dims, so
    the single-device Executor path cannot run this build).  On a CPU
    host the mesh is the virtual 8-device test mesh; on chip it is the
    tunnel's slice.  Emits ONE JSON line with tokens/s, the tp walker
    verdict (`analyze_program(tp_degree=)`), and the per-axis wire
    split (`collective_wire_bytes_by_axis`, mp ring at its own degree,
    batch-bound) riding ``memory_knobs``."""
    tp = _tp_knob()
    if tp <= 1:
        raise SystemExit("bench --tp: a tensor-parallel degree >= 2 is "
                         "required in this mode (use the default bench "
                         "for the tp-off baseline)")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    if os.environ.get("BENCH_FORCE_CPU") or not os.environ.get(
            "BENCH_AUTO_TPU"):
        jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.static as static
    from paddle_tpu.core import compile_cache
    from paddle_tpu.core.program import _reset_unique_names
    from paddle_tpu.distributed.compiled_program import (CompiledProgram,
                                                         BuildStrategy,
                                                         insert_grad_allreduce)

    devices = jax.devices()
    on_tpu = devices[0].platform != "cpu"
    want_world = int(os.environ.get("BENCH_WORLD", "0"))
    world = min(want_world, len(devices)) if want_world else len(devices)
    if world % tp != 0 or world < tp:
        raise SystemExit(
            f"bench --tp: world {world} does not hold a tp={tp} mesh")
    dp_world = world // tp
    seq = int(os.environ.get("BENCH_SEQ", 512 if on_tpu else 32))
    layers_n = int(os.environ.get("BENCH_LAYERS", 12 if on_tpu else 2))
    hidden = int(os.environ.get("BENCH_HIDDEN", 768 if on_tpu else 64))
    heads = int(os.environ.get("BENCH_HEADS", 12 if on_tpu else 4))
    vocab = int(os.environ.get("BENCH_VOCAB", 30522 if on_tpu else 256))
    batch = int(os.environ.get("BENCH_BATCH", 64 if on_tpu else 4))
    steps = int(os.environ.get("BENCH_STEPS", 20 if on_tpu else 6))

    from paddle_tpu.models import build_transformer_lm
    _reset_unique_names()
    main_p, startup_p, loss, _ = build_transformer_lm(
        vocab_size=vocab, hidden=hidden, num_layers=layers_n,
        num_heads=heads, seq_len=seq, tensor_parallel_degree=tp)
    with static.program_guard(main_p, startup_p):
        static.Adam(learning_rate=1e-4).minimize(loss)

    # compile-time story: tp walker verdict + per-axis wire, recorded
    # before a single device cycle is spent
    _mem = static.analyze_program(main_p, batch=batch, tp_degree=tp)
    reduced = insert_grad_allreduce(main_p)
    wire_axis = static.collective_wire_bytes_by_axis(reduced, dp_world,
                                                     batch=batch)

    bs = BuildStrategy()
    bs.tensor_parallel_degree = tp
    cp = CompiledProgram(main_p).with_data_parallel(
        loss_name=loss.name, build_strategy=bs,
        places=list(devices)[:world])
    exe = static.Executor()
    scope = static.Scope()
    rng = np.random.RandomState(0)
    idt = np.int64 if jax.config.jax_enable_x64 else np.int32
    gb = batch * dp_world
    feed = {"ids": rng.randint(0, vocab, (gb, seq)).astype(idt),
            "pos": np.tile(np.arange(seq), (gb, 1)).astype(idt),
            "labels": rng.randint(0, vocab, (gb, seq, 1)).astype(idt)}
    with static.scope_guard(scope):
        exe.run(startup_p)
        exe.run(cp, feed=feed, fetch_list=[loss])      # warm/compile
        exe.run(cp, feed=feed, fetch_list=[])
        warm_traces = compile_cache.cache_stats()["traces"]
        t0 = time.time()
        for _ in range(steps - 1):
            exe.run(cp, feed=feed, fetch_list=[])
        out = exe.run(cp, feed=feed, fetch_list=[loss])
        np.asarray(out[0])
        dt = time.time() - t0
    retraces = compile_cache.cache_stats()["traces"] - warm_traces
    tokens_per_sec = steps * gb * seq / dt / world  # per chip
    result = {
        "metric": "tp_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s/chip",
        "on_tpu": on_tpu,
        "mesh": {"dp": dp_world, "tp": tp},
        "seq": seq,
        "global_batch": gb,
        "measured_step_ms": round(dt / steps * 1e3, 2),
        "retraces_after_warmup": int(retraces),
        "predicted_peak_bytes": _mem["peak_bytes"],
        "predicted_fits": _mem["fits"],
        "hbm_budget_bytes": _mem["budget_bytes"],
        "memory_knobs": {"remat": "off", "grad_merge_k": 0,
                         "ring": False, "dp_shard": 0, "zero_stage": 0,
                         "tp_degree": tp},
        "collective_bytes_per_step": {"wire_bytes_per_axis": wire_axis},
    }
    assert retraces == 0, "bench --tp: recompile inside the timed loop"
    if not on_tpu:
        result["failed"] = True
        result["note"] = ("CPU mesh run; the walker/wire predictions "
                          "are the deliverable")
    print(json.dumps(result))


def auto_main():
    """Auto-parallel planner mode (`python bench.py --auto` or
    BENCH_MODE=auto): build the bench model, let
    `static.plan_program` search the knob lattice (batch x remat x
    dp_shard x grad_merge x bucket-MB x ring variant) against the
    three-substrate cost model, APPLY the chosen plan
    (`static.apply_plan` — recorded in the applied-passes registry, so
    the verifier's V504 drift check guards later hand-edits), and run
    it data-parallel over the local mesh — the timed loop rides the
    SCANNED micro-step window (`Executor.run_steps`, K steps per device
    dispatch, commit tail hoisted when the plan says so) unless
    BENCH_AUTO_SCAN=0.  Every record stamps predicted_vs_measured_pct,
    the calibrated roofline's wall-clock error on this host
    (tools/calibrate_roofline.py).  `--dry-run` (BENCH_AUTO_DRY=1)
    stops after plan+apply and prints the plan — the path
    tools/plan_smoke.py gates.  Prints ONE JSON line."""
    dry = "--dry-run" in sys.argv or \
        os.environ.get("BENCH_AUTO_DRY", "") not in ("", "0", "false")
    want_world = int(os.environ.get("BENCH_WORLD", "0"))
    # the mode targets the LOCAL mesh; on a CPU host grow a virtual
    # 8-device mesh (same as the test conftest) — a no-op if jax
    # already initialized its backend, and ignored on TPU hosts where
    # jax.devices() is the real slice
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count="
            f"{want_world or 8}").strip()
    import jax
    if os.environ.get("BENCH_FORCE_CPU") or not os.environ.get(
            "BENCH_AUTO_TPU"):
        jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.static as static
    from paddle_tpu.core import compile_cache
    from paddle_tpu.core.program import _reset_unique_names
    from paddle_tpu.distributed.compiled_program import CompiledProgram

    devices = jax.devices()
    on_tpu = devices[0].platform != "cpu"
    world = min(want_world, len(devices)) if want_world else len(devices)
    seq = int(os.environ.get("BENCH_SEQ", 512 if on_tpu else 64))
    layers_n = int(os.environ.get("BENCH_LAYERS", 12 if on_tpu else 2))
    hidden = int(os.environ.get("BENCH_HIDDEN", 768 if on_tpu else 128))
    heads = int(os.environ.get("BENCH_HEADS", 12 if on_tpu else 4))
    vocab = int(os.environ.get("BENCH_VOCAB", 30522 if on_tpu else 1024))
    use_amp = os.environ.get("BENCH_NO_AMP", "") in ("", "0", "false")
    batch = int(os.environ.get("BENCH_BATCH", "0")) or None
    steps = int(os.environ.get("BENCH_STEPS", 20 if on_tpu else 8))

    # BENCH_TP=1 / BENCH_TP_DEGREES=2,4 put the tensor-parallel axis on
    # the lattice: tp variants are auto-generated from the model config
    # through the tensor_parallel builders (no hand-feeding the winner),
    # so the BASE build uses the same static LM builder for an
    # apples-to-apples trace.  BENCH_GLOBAL_BATCH=G arms the
    # effective-global-batch constraint (gm×tp candidates can win).
    tp_env = os.environ.get("BENCH_TP_DEGREES", "")
    want_tp = tuple(int(x) for x in tp_env.split(",") if x.strip())
    use_tp_lattice = bool(want_tp) or \
        os.environ.get("BENCH_TP", "") not in ("", "0", "false")
    global_batch = int(os.environ.get("BENCH_GLOBAL_BATCH", "0")) or None

    def build(use_ring):
        _reset_unique_names()
        if use_tp_lattice:
            from paddle_tpu.models import build_transformer_lm
            main_b, startup_b, loss_b, _ = build_transformer_lm(
                vocab_size=vocab, hidden=hidden, num_layers=layers_n,
                num_heads=heads, seq_len=seq)
            with static.program_guard(main_b, startup_b):
                static.Adam(learning_rate=1e-4).minimize(loss_b)
            return main_b, startup_b, loss_b
        return build_bert_base(vocab, seq, hidden, layers_n, heads,
                               batch or 8, use_amp=use_amp,
                               use_ring=use_ring)

    from paddle_tpu.core.pass_framework import applied_passes
    t_plan = time.time()
    main_p, startup_p, loss = build(use_ring=False)
    variants = {}
    if seq >= 2048 and not use_tp_lattice:
        # the long-seq regime where the ring knob is worth searching;
        # ring attention is emitted at BUILD time, so it enters the
        # lattice as a program variant
        ring_main, ring_startup, ring_loss = build(use_ring=True)
        variants["ring"] = (ring_main, ring_startup)
    # CPU lattice keeps batches small so the proof run stays cheap;
    # the chip lattice searches the full default buckets
    knobs = None
    if not on_tpu and batch is None:
        knobs = {"batch": (2, 4, 8)}
    model_config = None
    if use_tp_lattice:
        model_config = dict(vocab_size=vocab, hidden=hidden,
                            num_layers=layers_n, num_heads=heads,
                            seq_len=seq, learning_rate=1e-4)
        if want_tp:
            knobs = dict(knobs or {})
            knobs["tp_degree"] = (0,) + want_tp
    plan = static.plan_program(main_p, startup_p, world=world,
                               batch=batch, knobs=knobs,
                               variants=variants or None,
                               model_config=model_config,
                               global_batch=global_batch)
    if plan.knobs["ring"]:
        main_p, startup_p, loss = ring_main, ring_startup, ring_loss
    tp_chosen = int(plan.knobs.get("tp_degree") or 0)
    if tp_chosen > 1:
        main_p, startup_p, loss = plan.build_variants[tp_chosen]
    static.apply_plan(main_p, startup_p, plan)
    plan_wall = time.time() - t_plan

    result = {
        "metric": "auto_plan_tokens_per_sec",
        "value": 0.0,
        "unit": "tokens/s/chip",
        "on_tpu": on_tpu,
        "world": world,
        "seq": seq,
        "plan": plan.to_dict(),
        "plan_wall_s": round(plan_wall, 2),
        "applied_passes": [e["pass"] for e in applied_passes(main_p)],
    }
    if dry:
        result["dry_run"] = True
        print(json.dumps(result))
        return

    b = plan.batch
    dp_world = world // tp_chosen if tp_chosen > 1 else world
    gb = b * dp_world
    loss_name = loss if isinstance(loss, str) else loss.name
    bs_build = None
    if tp_chosen > 1:
        from paddle_tpu.distributed.compiled_program import BuildStrategy
        bs_build = BuildStrategy()
        bs_build.tensor_parallel_degree = tp_chosen
        result["mesh"] = {"dp": dp_world, "tp": tp_chosen}
    cp = CompiledProgram(main_p).with_data_parallel(
        loss_name=loss_name, build_strategy=bs_build,
        places=list(devices)[:world])
    exe = static.Executor()
    scope = static.Scope()
    rng = np.random.RandomState(0)
    idt = np.int64 if jax.config.jax_enable_x64 else np.int32
    feed = {"ids": rng.randint(0, vocab, (gb, seq)).astype(idt),
            "pos": np.tile(np.arange(seq), (gb, 1)).astype(idt),
            "labels": rng.randint(0, vocab, (gb, seq, 1)).astype(idt)}
    # the scanned micro-step window is the DEFAULT timed hot path: K
    # steps ride ONE jitted lax.scan dispatch (Executor.run_steps), and
    # when the plan chose scan_hoist the window's commit tail (optimizer
    # update + publish allgather) runs once per window instead of once
    # per masked micro-step.  K follows the gm window so the hoist gate
    # engages; BENCH_AUTO_SCAN=0 falls back to the per-step loop.
    use_scan = os.environ.get("BENCH_AUTO_SCAN", "") not in ("0", "false")
    gm_k = max(1, int(plan.knobs.get("grad_merge") or 1))
    scan_k = gm_k if gm_k > 1 else min(4, steps)
    windows = max(1, steps // scan_k)
    with static.scope_guard(scope):
        exe.run(startup_p)
        if use_scan:
            steps = windows * scan_k
            sfeed = {n: np.stack([v] * scan_k) for n, v in feed.items()}
            outs = exe.run_steps(cp, feed=sfeed, fetch_list=[loss])
            warm_traces = compile_cache.cache_stats()["traces"]
            t0 = time.time()
            for _ in range(windows):
                outs = exe.run_steps(cp, feed=sfeed, fetch_list=[loss])
            np.asarray(outs[0])
            dt = time.time() - t0
        else:
            exe.run(cp, feed=feed, fetch_list=[loss])      # warm/compile
            exe.run(cp, feed=feed, fetch_list=[])
            warm_traces = compile_cache.cache_stats()["traces"]
            t0 = time.time()
            for _ in range(steps - 1):
                exe.run(cp, feed=feed, fetch_list=[])
            out = exe.run(cp, feed=feed, fetch_list=[loss])
            np.asarray(out[0])
            dt = time.time() - t0
    retraces = compile_cache.cache_stats()["traces"] - warm_traces
    tokens_per_sec = steps * gb * seq / dt / world  # per chip
    result["value"] = round(tokens_per_sec, 2)
    result["measured_step_ms"] = round(dt / steps * 1e3, 2)
    result["retraces_after_warmup"] = int(retraces)
    if use_scan:
        result["scan"] = {
            "k": scan_k, "windows": windows,
            "hoisted": "scan_hoist" in result["applied_passes"],
        }
    # calibration loop closure (tools/calibrate_roofline.py): when the
    # checked-in fit is trusted, predicted_step_ms is a wall-clock
    # estimate of THIS host class — stamp its error on every record so
    # drift between the fit and reality is visible in the artifact
    result["predicted_vs_measured_pct"] = round(
        abs(plan.predicted_step_ms - dt / steps * 1e3)
        / max(dt / steps * 1e3, 1e-9) * 100, 1)
    assert retraces == 0, "bench --auto: recompile inside the timed loop"
    if not on_tpu:
        result["failed"] = True
        result["note"] = ("CPU mesh run; the planner's predicted "
                          "numbers are the deliverable")
    print(json.dumps(result))


def scan_main():
    """Scanned-window A/B (`python bench.py --scan` or BENCH_MODE=scan):
    build the bench model under ZeRO (BENCH_DP_SHARD / BENCH_ZERO_STAGE,
    default stage-2 over 8 ranks) x gradient merge (BENCH_GRAD_MERGE,
    default K=4) and measure the SAME window both ways — K looped
    `Executor.run` dispatches vs ONE `Executor.run_steps` scanned
    dispatch with the commit tail (optimizer update + publish
    allgather) hoisted out of the scan body
    (distributed/scan_window).  Stamps the ring-accounted per-step wire
    of both paths (`scan_window_wire_bytes`: the looped path re-publishes
    masked-out state K times per window, the hoisted path once) and the
    dispatch counts.  Prints ONE JSON line."""
    dp = int(os.environ.get("BENCH_DP_SHARD", "8"))
    stage = int(os.environ.get("BENCH_ZERO_STAGE", "2"))
    gm_k = max(2, int(os.environ.get("BENCH_GRAD_MERGE", "4")))
    want_world = int(os.environ.get("BENCH_WORLD", "8"))
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count="
            f"{want_world}").strip()
    import jax
    if os.environ.get("BENCH_FORCE_CPU") or not os.environ.get(
            "BENCH_SCAN_TPU"):
        jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.static as static
    from paddle_tpu.core import compile_cache
    from paddle_tpu.core.program import _reset_unique_names
    from paddle_tpu.distributed import scan_window_wire_bytes
    from paddle_tpu.distributed.compiled_program import CompiledProgram
    from paddle_tpu.distributed.sharding import shard_optimizer_states

    devices = jax.devices()
    on_tpu = devices[0].platform != "cpu"
    world = min(want_world, len(devices))
    seq = int(os.environ.get("BENCH_SEQ", 512 if on_tpu else 64))
    layers_n = int(os.environ.get("BENCH_LAYERS", 12 if on_tpu else 2))
    hidden = int(os.environ.get("BENCH_HIDDEN", 768 if on_tpu else 128))
    heads = int(os.environ.get("BENCH_HEADS", 12 if on_tpu else 4))
    vocab = int(os.environ.get("BENCH_VOCAB", 30522 if on_tpu else 1024))
    batch = int(os.environ.get("BENCH_BATCH", 32 if on_tpu else 2))
    windows = int(os.environ.get("BENCH_SCAN_WINDOWS", 8 if on_tpu else 3))
    use_amp = os.environ.get("BENCH_NO_AMP", "") in ("", "0", "false")

    _reset_unique_names()
    main_p, startup_p, loss = build_bert_base(
        vocab, seq, hidden, layers_n, heads, batch, use_amp=use_amp)
    if dp > 1:
        shard_optimizer_states(main_p, startup_p,
                               dp_degree=min(dp, world), stage=stage)
    static.gradient_merge(main_p, gm_k, startup_program=startup_p)
    gb = batch * world
    wire = scan_window_wire_bytes(main_p, world, batch=gb)

    cp = CompiledProgram(main_p).with_data_parallel(
        loss_name=loss.name, places=list(devices)[:world])
    exe = static.Executor()
    scope = static.Scope()
    rng = np.random.RandomState(0)
    idt = np.int64 if jax.config.jax_enable_x64 else np.int32
    feed = {"ids": rng.randint(0, vocab, (gb, seq)).astype(idt),
            "pos": np.tile(np.arange(seq), (gb, 1)).astype(idt),
            "labels": rng.randint(0, vocab, (gb, seq, 1)).astype(idt)}
    sfeed = {n: np.stack([v] * gm_k) for n, v in feed.items()}
    with static.scope_guard(scope):
        exe.run(startup_p)
        # looped side: K host dispatches per window.  Warm a full gm
        # window so the host micro-step counter stays window-aligned —
        # the hoist gate only engages at a window boundary.
        exe.run(cp, feed=feed, fetch_list=[loss])
        for _ in range(gm_k - 2):
            exe.run(cp, feed=feed, fetch_list=[])
        exe.run(cp, feed=feed, fetch_list=[])
        d0 = cp._dispatches
        t0 = time.time()
        for _ in range(windows * gm_k - 1):
            exe.run(cp, feed=feed, fetch_list=[])
        out = exe.run(cp, feed=feed, fetch_list=[loss])
        np.asarray(out[0])
        looped_ms = (time.time() - t0) / (windows * gm_k) * 1e3
        looped_disp = cp._dispatches - d0
        # scanned-hoisted side: ONE dispatch per window
        outs = exe.run_steps(cp, feed=sfeed, fetch_list=[loss])  # warm
        warm_traces = compile_cache.cache_stats()["traces"]
        d0 = cp._dispatches
        t0 = time.time()
        for _ in range(windows):
            outs = exe.run_steps(cp, feed=sfeed, fetch_list=[loss])
        np.asarray(outs[0])
        scanned_ms = (time.time() - t0) / (windows * gm_k) * 1e3
        scanned_disp = cp._dispatches - d0
    retraces = compile_cache.cache_stats()["traces"] - warm_traces

    result = {
        "metric": "scan_hoist_wire_ratio",
        "value": round(wire["per_step_looped"]
                       / max(wire["per_step_hoisted"], 1e-9), 4),
        "unit": "looped/hoisted per-step ICI bytes",
        "on_tpu": on_tpu,
        "world": world, "seq": seq, "batch": batch,
        "dp_shard": min(dp, world), "zero_stage": stage,
        "grad_merge": gm_k, "windows": windows,
        "wire_bytes": {k: round(v, 1) if isinstance(v, float) else v
                       for k, v in wire.items()},
        "looped_step_ms": round(looped_ms, 2),
        "scanned_step_ms": round(scanned_ms, 2),
        "dispatches_per_window": {"looped": looped_disp // windows,
                                  "scanned": scanned_disp // windows},
        "retraces_after_warmup": int(retraces),
    }
    assert retraces == 0, "bench --scan: recompile inside the timed loop"
    if not on_tpu:
        result["failed"] = True
        result["note"] = ("CPU mesh run; the wire accounting and "
                          "dispatch counts are the deliverable")
    print(json.dumps(result))


def _probe_tpu():
    """Device discovery over the axon tunnel can hang inside a C call, so
    probe in SUBPROCESSES with hard timeouts.  A CPU fallback is a FAILED
    perf run (VERDICT r2: the probe must retry, not silently fall back) —
    retry with backoff for a total budget >= 10 min before giving up, and
    carry the reason into the emitted JSON."""
    import subprocess
    retries = int(os.environ.get("BENCH_TPU_PROBE_RETRIES", "5"))
    probe_s = int(os.environ.get("BENCH_TPU_PROBE_TIMEOUT", "120"))
    last = "unknown"
    for attempt in range(1, retries + 1):
        try:
            subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=probe_s, check=True, capture_output=True)
            return True, ""
        except subprocess.TimeoutExpired:
            last = f"device discovery timed out ({probe_s}s)"
        except subprocess.CalledProcessError as e:
            tail = (e.stderr or b"")[-200:].decode("utf-8", "replace")
            last = f"device discovery failed: {tail!r}"
        sys.stderr.write(
            f"bench: TPU probe attempt {attempt}/{retries} failed "
            f"({last})\n")
        if attempt < retries:
            time.sleep(min(30 * attempt, 120))
    return False, last


def main():
    global _FALLBACK_NOTE
    if "--serving" in sys.argv or \
            os.environ.get("BENCH_MODE") == "serving":
        serving_main()
        return
    if "--checkpoint" in sys.argv or \
            os.environ.get("BENCH_MODE") == "checkpoint":
        checkpoint_main()
        return
    if "--elastic" in sys.argv or \
            os.environ.get("BENCH_MODE") == "elastic":
        elastic_main()
        return
    if "--seq-ladder" in sys.argv or \
            os.environ.get("BENCH_MODE") == "seq_ladder":
        seq_ladder_main()
        return
    if "--auto" in sys.argv or os.environ.get("BENCH_MODE") == "auto":
        auto_main()
        return
    if "--scan" in sys.argv or os.environ.get("BENCH_MODE") == "scan" \
            or os.environ.get("BENCH_SCAN", "") not in ("", "0", "false"):
        scan_main()
        return
    # --tp 1 / --tp 0 explicitly ask for the NO-tensor-parallel
    # baseline: fall through to the default bench instead of silently
    # measuring a tp mesh
    if _tp_knob() > 1:
        tp_main()
        return
    # allow CPU fallback benchmarking only when explicitly requested or
    # after the full retry budget is exhausted
    if os.environ.get("BENCH_FORCE_CPU"):
        import jax
        jax.config.update("jax_platforms", "cpu")
    else:
        ok, reason = _probe_tpu()
        if not ok:
            os.environ["BENCH_FORCE_CPU"] = "1"
            os.environ["JAX_PLATFORMS"] = "cpu"
            os.environ["BENCH_FALLBACK_NOTE"] = (
                f"TPU unreachable after retries: {reason}")
            sys.stderr.write(
                "bench: TPU unreachable after full retry budget; "
                "re-running on CPU (recorded as a FAILED perf run)\n")
            os.execv(sys.executable, [sys.executable, __file__])
    _FALLBACK_NOTE = os.environ.get("BENCH_FALLBACK_NOTE", "")
    import jax
    import jax.numpy as jnp
    import paddle_tpu.static as static
    from paddle_tpu.core import compile_cache
    from paddle_tpu.ops.attention import enable_flash_attention

    # persistent XLA cache (PADDLE_TPU_CACHE_DIR): a warm second run loads
    # serialized executables instead of re-compiling — on the ~30-minute
    # axon tunnel window, compile minutes are measurement minutes
    compile_cache.initialize()
    warm_entries = compile_cache.persistent_entries()

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    # batch 64 is the measured single-chip sweet spot (r5 sweep: b32
    # 35.9k tok/s, b64 85k, b96/b128 OOM 15.75G HBM)
    seq, batch = (512, 64) if on_tpu else (128, 2)
    layers_n = 12 if on_tpu else 2
    hidden = 768 if on_tpu else 256
    heads = 12 if on_tpu else 4
    vocab = 30522 if on_tpu else 1024
    batch = int(os.environ.get("BENCH_BATCH", batch))
    seq = int(os.environ.get("BENCH_SEQ", seq))
    # model-shape overrides (e.g. ERNIE-large: LAYERS=24 HIDDEN=1024
    # HEADS=16 BATCH=16 — BASELINE.md config 5's model on one chip)
    layers_n = int(os.environ.get("BENCH_LAYERS", layers_n))
    hidden = int(os.environ.get("BENCH_HIDDEN", hidden))
    heads = int(os.environ.get("BENCH_HEADS", heads))
    use_amp = os.environ.get("BENCH_NO_AMP", "") in ("", "0", "false")

    # Flash dispatch is seq-length AUTO by default (crossover flag
    # flash_min_seq_len).  r5 on-chip A/Bs: XLA attention wins at every
    # length where both fit (512/2048/4096), so auto selects flash only
    # from 8192 up, where materialized scores OOM.  BENCH_FLASH=1/0
    # forces it for A/B runs.
    if os.environ.get("BENCH_FLASH", "") != "":
        enable_flash_attention(
            os.environ["BENCH_FLASH"] not in ("0", "false"))
    # BENCH_FUSED_CE=1: route the [tokens, vocab] cross-entropy through
    # the Pallas online fused kernel for A/B (tools/tune_fused_xent.py)
    if os.environ.get("BENCH_FUSED_CE", "") not in ("", "0", "false"):
        from paddle_tpu.ops.fused_xent import enable_fused_xent
        enable_fused_xent(True)

    # BENCH_REMAT=1/auto (--remat): activation checkpointing at
    # transformer-layer boundaries (static/recompute_rewrite.py) — the
    # memory-for-throughput knob the b96/b128 A/B decides.  "auto"
    # rewrites only when the HBM estimator predicts this batch exceeds
    # PADDLE_TPU_HBM_BYTES.  BENCH_GRAD_MERGE=K (--grad-merge K):
    # k-step gradient accumulation (static.gradient_merge), the OTHER
    # way to trade per-step memory for effective batch.  BENCH_RING=1
    # (--ring): ring-attention op in every layer.  NOTE on one chip
    # (this bench's Executor path) the op degrades to plain attention —
    # the A/B measures the op's dispatch overhead and composes with
    # remat; the estimator charges the degraded kernel's full S² scores
    # (memory_analysis._op_internal_bytes), and the true sp-sharded
    # numbers need CompiledProgram over a multi-chip mesh.
    remat_mode, grad_merge_k, use_ring = _bench_knobs()
    # BENCH_DP_SHARD=N (--dp-shard [N]) + BENCH_ZERO_STAGE=S
    # (--zero-stage S): ZeRO sharding A/B at stages 1-3.  The rewrite is
    # applied for an N-rank dp world; on this bench's single-device
    # Executor path every collective degrades to identity, so tokens/s
    # measures the rewrite's dispatch/fusion overhead while
    # predicted_peak_bytes and collective_bytes report the N-chip story
    # (the mesh numbers need CompiledProgram over real chips — queued as
    # zero1_*/zero2_*/zero3_* in perf_r05/queue.txt).
    dp_shard = _dp_shard_knob()
    zero_stage = _zero_stage_knob()
    if remat_mode:
        from paddle_tpu.core.flags import set_flags
        set_flags({"recompute": remat_mode, "hbm_assume_batch": batch,
                   "hbm_dp_shard": dp_shard,
                   "hbm_zero_stage": zero_stage if dp_shard > 1 else 0})

    main_p, startup_p, loss = build_bert_base(vocab, seq, hidden, layers_n,
                                              heads, batch, use_amp=use_amp,
                                              use_ring=use_ring)
    if remat_mode:
        from paddle_tpu.core.flags import set_flags
        set_flags({"recompute": "", "hbm_assume_batch": 0,
                   "hbm_dp_shard": 0, "hbm_zero_stage": 0})
    _collective_bytes = None
    if dp_shard > 1:
        from paddle_tpu.distributed.compiled_program import \
            insert_grad_allreduce
        from paddle_tpu.distributed.sharding import shard_optimizer_states
        # wire accounting rides the verifier's ring-accounted extractor
        # (static.collective_wire_bytes — the planner's wire substrate;
        # ring 0 = the dist-pass gradient/param collectives, matching
        # the A/B's historical scope; the per-bucket
        # sharding.collective_bytes_per_step shim is retired).
        # plain-DP wire bytes: what insert_grad_allreduce WOULD emit for
        # this program on an N-rank mesh (per-param allreduce)
        plain_bytes = static.collective_wire_bytes(
            insert_grad_allreduce(main_p), dp_shard, ring_id=0)
        shard_optimizer_states(main_p, startup_p, dp_degree=dp_shard,
                               stage=zero_stage)
        reduced = insert_grad_allreduce(main_p)
        zero_bytes = static.collective_wire_bytes(reduced, dp_shard,
                                                 ring_id=0)
        # every ring (dist-pass rs/ag plus forward model-parallel
        # collectives) — reported alongside the ring-0 A/B numbers so
        # the full wire story stays visible
        wire_all = static.collective_wire_bytes(reduced, dp_shard)
        # per-mesh-axis split: each ring priced at its OWN degree
        # (tensor-ring collectives never pay the dp world) — the wire
        # substrate the 2-D planner consumes; batch bound so mp-ring
        # activation collectives price
        wire_axis = static.collective_wire_bytes_by_axis(reduced, dp_shard,
                                                         batch=batch)
        _collective_bytes = {"allreduce": plain_bytes,
                             f"zero{zero_stage}": zero_bytes,
                             f"zero{zero_stage}_all_rings": wire_all,
                             "wire_bytes_per_axis": wire_axis}
    if grad_merge_k > 1:
        static.gradient_merge(main_p, grad_merge_k, startup_p)
    # compile-time HBM verdict rides every bench record: the number that
    # decides fits-or-OOMs before a tunnel window is ever spent
    _mem = static.analyze_program(main_p, batch=batch,
                                  dp_shard=dp_shard or None,
                                  zero_stage=(zero_stage
                                              if dp_shard > 1 else None))
    exe = static.Executor()
    scope = static.Scope()
    rng = np.random.RandomState(0)

    # int32 feeds on x64-disabled backends (the default): int64 would be
    # truncated on device anyway, each transfer paying a UserWarning +
    # an extra cast (the BENCH_r05 log tail)
    idt = np.int64 if jax.config.jax_enable_x64 else np.int32

    def batch_feed():
        return {
            "ids": rng.randint(0, vocab, (batch, seq)).astype(idt),
            "pos": np.tile(np.arange(seq), (batch, 1)).astype(idt),
            "labels": rng.randint(0, vocab,
                                  (batch, seq, 1)).astype(idt),
        }

    # Megastep: scan K training steps inside ONE jitted dispatch
    # (Executor.run_steps).  Per-dispatch host/tunnel latency measured r5
    # at ~300 ms/step vs 155 ms/step device compute (batch 32) — the
    # device-resident loop is how the chip's real rate becomes the wall
    # rate.  BENCH_MEGASTEP=0 falls back to one-dispatch-per-step.
    # 30 CPU steps: the 10-step window was ~1s of wall and swung ±10%
    # run-to-run, drowning real deltas in noise
    n_steps = int(os.environ.get("BENCH_STEPS", 20 if on_tpu else 30))
    megastep = int(os.environ.get("BENCH_MEGASTEP",
                                  n_steps if on_tpu else 0))
    device_feed = os.environ.get("BENCH_DEVICE_FEED", "") not in ("", "0")
    compile_time_s = 0.0
    with static.scope_guard(scope):
        exe.run(startup_p)
        feed = batch_feed()
        if device_feed and megastep <= 0:
            # pre-stage the feed on device ONCE: isolates per-step
            # host->device transfer cost (high-latency axon tunnel) from
            # compute
            feed = {k: jax.device_put(jnp.asarray(v), dev)
                    for k, v in feed.items()}
        prof_dir = os.environ.get("BENCH_PROFILE", "")
        if megastep > 0:
            sfeed = {k: np.broadcast_to(np.asarray(v),
                                        (megastep,) + np.shape(v)).copy()
                     for k, v in feed.items()}
            if device_feed:
                sfeed = {k: jax.device_put(jnp.asarray(v), dev)
                         for k, v in sfeed.items()}
            try:
                # warmup compiles the scan; timed run is ONE dispatch
                tc = time.time()
                exe.run_steps(main_p, feed=sfeed, fetch_list=[loss])
                compile_time_s = time.time() - tc
            except Exception as e:  # pragma: no cover - chip-side safety
                # the scanned path must never cost the round its number:
                # fall back to one-dispatch-per-step and say so.  A
                # runtime failure happens AFTER the state buffers were
                # donated to the scan, so re-init them before the
                # fallback reads the scope; device_feed staging was also
                # skipped when megastep was on — do it now.
                sys.stderr.write(
                    f"bench: megastep path failed ({e!r}); falling back "
                    f"to per-step dispatch\n")
                megastep = 0
                exe.run(startup_p)
                if device_feed:
                    feed = {k: jax.device_put(jnp.asarray(v), dev)
                            for k, v in feed.items()}
        if megastep > 0:
            n_steps = megastep
            if prof_dir:
                from paddle_tpu.profiler import set_device_trace_active
                jax.profiler.start_trace(prof_dir)
                set_device_trace_active(True)
            t0 = time.time()
            out = exe.run_steps(main_p, feed=sfeed, fetch_list=[loss])
            np.asarray(out[0])
            dt = time.time() - t0
        else:
            # warmup/compile BOTH step signatures (fetch + no-fetch differ
            # in cache key; compiling inside the timed loop poisons dt —
            # and poisons the HEADLINE: compile_time_s is reported as its
            # own JSON field so a cold cache can't drag down tokens/s)
            tc = time.time()
            exe.run(main_p, feed=feed, fetch_list=[loss])
            exe.run(main_p, feed=feed, fetch_list=[])
            compile_time_s = time.time() - tc
            warm_traces = exe.cache_stats()["traces"]
            if prof_dir:
                from paddle_tpu.profiler import set_device_trace_active
                jax.profiler.start_trace(prof_dir)
                set_device_trace_active(True)
            t0 = time.time()
            # steps WITHOUT per-step fetches: state buffers are donated
            # and stay on device, dispatch runs ahead of the chip; only
            # the last step fetches the loss (forces completion).  Feeds
            # ride the async Prefetcher: batch N+1's host-side cast +
            # device_put overlaps batch N's step (reader/prefetcher.py).
            # BENCH_PREFETCH=auto: on-chip the host is idle during the
            # step so overlap is free; on CPU the worker thread would
            # STEAL cores from XLA compute (measured -25% on a 2-core
            # box), so the plain loop wins there.
            prefetch = os.environ.get("BENCH_PREFETCH", "auto")
            use_prefetch = on_tpu if prefetch == "auto" \
                else prefetch not in ("0", "false")
            if use_prefetch:
                feeds = (feed for _ in range(n_steps - 1))
                for _ in exe.run_prefetched(main_p, feeds, fetch_list=[],
                                            return_numpy=False):
                    pass
            else:
                for _ in range(n_steps - 1):
                    exe.run(main_p, feed=feed, fetch_list=[])
            out = exe.run(main_p, feed=feed, fetch_list=[loss])
            np.asarray(out[0])
            dt = time.time() - t0
            assert exe.cache_stats()["traces"] == warm_traces, \
                "recompile inside the timed loop"
        if prof_dir:
            from paddle_tpu.profiler import set_device_trace_active
            jax.profiler.stop_trace()
            set_device_trace_active(False)

    tokens_per_sec = n_steps * batch * seq / dt

    # MFU accounting, twice over and cross-checked:
    #   analytic — 6 * params * tokens (fwd+bwd matmul flops) PLUS the
    #   attention score/context matmuls the params-only count misses —
    #   QK^T and PV are each 2*s*hidden flops per token per layer
    #   forward, 3x that with backward: 12 * L * s * hidden per token;
    #   exact — static.analyze_flops walks the ACTUAL op list (so remat
    #   replays, ring degradation, AMP rewrites are all priced).  Both
    #   ride the JSON; >10% drift on a plain build means either the
    #   walker regressed or the analytic constants went stale, and the
    #   bench says so instead of silently reporting two truths.
    n_params = sum(
        int(np.prod(v.shape)) for v in main_p.all_parameters()
        if v.shape is not None)
    flops_per_token = 6 * n_params + 12 * layers_n * seq * hidden
    analytic_step_flops = flops_per_token * batch * seq
    walker_step_flops = static.analyze_flops(
        main_p, batch=batch)["total_flops"]
    flops_drift = walker_step_flops / analytic_step_flops - 1.0
    if abs(flops_drift) > 0.10 and not remat_mode:
        sys.stderr.write(
            f"bench: WARNING analyze_flops ({walker_step_flops:.3e}) "
            f"drifts {flops_drift * 100:+.1f}% from the analytic "
            f"estimate ({analytic_step_flops:.3e}) — walker regression "
            f"or stale analytic constants?\n")
    achieved = tokens_per_sec * flops_per_token
    peak = static.peak_flops_per_chip("tpu" if on_tpu else "cpu")
    mfu = achieved / peak if peak else 0.0
    mfu_exact = (tokens_per_sec / (batch * seq)) * walker_step_flops \
        / peak if peak else 0.0

    stats = exe.cache_stats()
    result = {
        "metric": "bert_base_pretrain_tokens_per_sec_per_chip"
                  if on_tpu else "bert_tiny_cpu_tokens_per_sec",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.35, 4) if peak else 0.0,
        # steady-state vs compile split: `value` is measured AFTER warmup;
        # a cold persistent cache shows up here, not in the headline
        "compile_time_s": round(compile_time_s, 2),
        # compile-time HBM accounting (static/memory_analysis.py)
        "predicted_peak_bytes": _mem["peak_bytes"],
        "predicted_fits": _mem["fits"],
        "hbm_budget_bytes": _mem["budget_bytes"],
        # per-op FLOPs accounting (static/flops_analysis.py): the exact
        # walked step cost next to the analytic formula, + their drift
        "flops_per_step_walked": walker_step_flops,
        "flops_per_step_analytic": analytic_step_flops,
        "flops_drift_pct": round(flops_drift * 100, 2),
        "cache": {
            "persistent_dir": stats["persistent_dir"],
            "warm_start": bool(warm_entries),
            "traces": stats["traces"],
            "hits": stats["hits"],
        },
    }
    if remat_mode or grad_merge_k > 1 or use_ring or dp_shard > 1:
        # self-describing A/B records: the queue runner's JSON says what
        # memory knobs produced the number
        result["memory_knobs"] = {"remat": remat_mode or "off",
                                  "grad_merge_k": grad_merge_k,
                                  "ring": use_ring,
                                  "dp_shard": dp_shard,
                                  "zero_stage": (zero_stage
                                                 if dp_shard > 1 else 0)}
    if _collective_bytes is not None:
        # per-rank ICI bytes per step: bucketed reduce-scatter+allgather
        # vs the per-param allreduce baseline (ring accounting)
        result["collective_bytes_per_step"] = _collective_bytes
        result["optimizer_slot_bytes"] = _mem["optimizer_slot_bytes"]
        result["parameter_bytes"] = _mem["parameter_bytes"]
    if on_tpu:
        result["mfu"] = round(mfu, 4)
        result["mfu_exact"] = round(mfu_exact, 4)
    else:
        # ANY CPU run is a FAILED perf run for the north-star record, and
        # says so explicitly — the driver must not read CPU tokens/s as
        # the perf headline.  The last-good on-chip number rides along so
        # a tunnel hang never erases what the chip already demonstrated
        # (VERDICT r5 weak-point 7).
        result["failed"] = True
        result["note"] = _FALLBACK_NOTE or \
            "CPU run (TPU not used); not comparable to the baseline"
        last = _last_known_tpu_metric()
        if last is not None:
            result["last_known_tpu"] = last
    print(json.dumps(result))


if __name__ == "__main__":
    main()

"""Calibrate the planner roofline against the LOCAL mesh
(static/planner.calibrate — the ISSUE 16 tentpole (d) loop closure).

The planner's roofline is a RANKING model: it divides walked FLOPs and
ring-accounted bytes by PEAK rates, so its absolute step times are
lower bounds and the argmax is all that is trusted.  This tool turns it
into a wall-clock estimator for one host class:

  1. builds a ladder of decision-table-shaped miniatures (fc towers,
     a plain batch ladder in one width/cache regime plus dp / ZeRO-1 /
     ZeRO-2×gm looped / ZeRO-2×gm scan-hoisted / ZeRO-3 at two
     widths) on the local mesh,
  2. prices each with `static.plan_program` pinned to exactly that knob
     point (verify off, calibration off — RAW roofline components), the
     compute leg denominated in a micro-measured host matmul rate,
  3. measures the same configuration's real per-step wall time
     (`Executor.run` loop for the looped rows; one
     `Executor.run_steps` scanned window / K for the hoisted row),
  4. fits `static.calibrate(pairs)` — per-class efficiencies for the
     compute / overlappable-wire / serial-wire legs plus a
     per-dispatch overhead intercept — and writes the fit + pairs to
     `perf_r05/roofline_calibration.json`.

`plan_program` auto-loads that file once its residual is under
`DEFAULT_CALIBRATION_RESIDUAL_PCT` (see `default_calibration`), so
checking the report in IS the flag flip that turns calibrated pricing
on for `bench.py --auto`.

Usage:
    python tools/calibrate_roofline.py            # fit + write JSON
    python tools/calibrate_roofline.py --report   # + markdown table
                                                  #   (docs/perf.md)
    python tools/calibrate_roofline.py --out PATH # alternate output
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("PADDLE_TPU_VERIFY", "warn")
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

WORLD = 8
STEPS = 10
GM_K = 4


def _host_peak_flops():
    """Micro-measured matmul rate of THIS host (flops/s): the compute
    leg's denominator.  Peak-ish, not sustained — the fitted
    eff_compute absorbs the gap, but starting from the right order of
    magnitude keeps the coefficient inside the fit's (1e-4, 1] window."""
    import jax
    import jax.numpy as jnp
    n = 512
    a = jnp.ones((n, n), jnp.float32)
    f = jax.jit(lambda x: x @ x)
    f(a).block_until_ready()          # compile outside the timing
    reps = 8
    t0 = time.time()
    for _ in range(reps):
        out = f(a)
    out.block_until_ready()
    dt = time.time() - t0
    return 2.0 * n ** 3 * reps / max(dt, 1e-9)


def _build(width, depth=4):
    import paddle_tpu.static as static
    from paddle_tpu.static import layers
    from paddle_tpu.core.program import _reset_unique_names
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, width])
        y = layers.data("y", [-1, 1])
        h = x
        for _ in range(depth):
            h = layers.fc(h, width, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.Adam(learning_rate=1e-2).minimize(loss)
    return main, startup, loss


def _apply(main, startup, spec):
    from paddle_tpu.distributed.sharding import shard_optimizer_states
    import paddle_tpu.static as static
    if spec.get("dp_shard"):
        shard_optimizer_states(main, startup, dp_degree=spec["dp_shard"],
                               stage=spec.get("zero_stage") or 1)
    if spec.get("grad_merge", 1) > 1:
        static.gradient_merge(main, spec["grad_merge"],
                              startup_program=startup)


def _predict(width, spec, batch, world, peak):
    """RAW roofline components of exactly this knob point."""
    import paddle_tpu.static as static
    main, startup, _ = _build(width)
    knobs = {"batch": (batch,),
             "remat": (False,),
             "dp_shard": (spec.get("dp_shard", 0),),
             "zero_stage": (spec.get("zero_stage", 0),),
             "grad_merge": (spec.get("grad_merge", 1),),
             "bucket_mb": (32,),
             "scan_hoist": (bool(spec.get("scan_hoist")),)}
    plan = static.plan_program(main, startup, world=world, knobs=knobs,
                               verify=False, calibration=False,
                               peak_flops=peak)
    c = plan.trace[0]
    for r in plan.trace:     # the pinned lattice still collapses a few
        if all(r[k] == v[0] for k, v in knobs.items() if k != "zero_stage"):
            c = r
            break
    return {"compute_ms": c["compute_ms"],
            "wire_overlap_ms": c["wire_overlap_ms"],
            "wire_serial_ms": c["wire_serial_ms"],
            "predicted_raw_ms": c["step_ms"]}


def _measure(width, spec, batch, world):
    """Best-of-3 measured per-step wall time of the same config (min
    discards scheduler noise on a shared host; the fit wants the
    repeatable floor, not the tail)."""
    import jax
    import numpy as np
    import paddle_tpu.static as static
    from paddle_tpu.distributed.compiled_program import CompiledProgram

    main, startup, loss = _build(width)
    _apply(main, startup, spec)
    gb = batch * world if world > 1 else batch
    rng = np.random.RandomState(0)
    hoist = bool(spec.get("scan_hoist"))
    k = spec.get("grad_merge", 1) if hoist else 1
    exe = static.Executor()
    scope = static.Scope()
    times = []
    with static.scope_guard(scope):
        prog = main
        if world > 1:
            prog = CompiledProgram(main).with_data_parallel(
                loss_name=loss.name,
                places=list(jax.devices())[:world])
        exe.run(startup)

        def one_feed(i):
            r = np.random.RandomState(i)
            return {"x": r.rand(gb, width).astype(np.float32),
                    "y": r.rand(gb, 1).astype(np.float32)}

        if hoist:
            window = {n: np.stack([one_feed(i)[n] for i in range(k)])
                      for n in ("x", "y")}
            exe.run_steps(prog, feed=window, fetch_list=[loss])  # warm
            for _ in range(3):
                t0 = time.time()
                outs = None
                for _ in range(max(1, STEPS // k)):
                    outs = exe.run_steps(prog, feed=window,
                                         fetch_list=[loss])
                np.asarray(outs[0])
                times.append((time.time() - t0) /
                             (max(1, STEPS // k) * k))
        else:
            f = one_feed(0)
            exe.run(prog, feed=f, fetch_list=[loss])          # warm
            for _ in range(3):
                t0 = time.time()
                for s in range(STEPS - 1):
                    exe.run(prog, feed=f, fetch_list=[])
                out = exe.run(prog, feed=f, fetch_list=[loss])
                np.asarray(out[0])
                times.append((time.time() - t0) / STEPS)
    return min(times) * 1e3   # ms


def _decode_pair(label, B, lc, W, tp, peak, weight_dtype="float32"):
    """One decode-step (memory-bound) calibration pair: the serving hot
    path is a tiny-FLOP, cache-dominated bucket, so its measured time is
    mostly dispatch intercept + mp wire — exactly the legs the training
    ladder under-constrains.  Prediction prices the REAL decode program
    (`serving.build_decode_program`): compute from the IR FLOP walk
    divided by tp (heads/MLP shard; the logits row is replicated but
    small at this geometry), serial wire from the per-layer Megatron
    collectives (two allreduces + the two KV gathers) over the ici
    rate.  Measurement drives `serving.TPShardedDecoder` — the same
    CompiledProgram the engine runs — best-of-3 over STEPS steps.

    At weight_dtype="int8" the program is first stamped through
    `slim.freeze_weights_int8` (the decoder applies the same stamp
    internally) and the int8 share of the walk is priced at
    `INT8_MXU_RATE` x the matmul rate — the v5e MXU claim the queued
    on-chip rows check; on this CPU host the decode step is
    intercept-dominated, so the fitted residual barely sees the rate
    and the pair's job is pinning the int8 wire/intercept shape."""
    import jax
    import numpy as np
    import paddle_tpu
    from paddle_tpu.models.gpt import GPTModel, GPTConfig
    from paddle_tpu.nn import MultiHeadAttention
    from paddle_tpu.serving.tp_decode import (TPShardedDecoder,
                                              build_decode_program,
                                              _param_map)
    from paddle_tpu.static.flops_analysis import (analyze_flops,
                                                  INT8_MXU_RATE)
    from paddle_tpu.static.planner import ici_bytes_per_chip

    cfg = GPTConfig(vocab_size=64, hidden_size=64, num_layers=2,
                    num_heads=4, max_position=256, dropout=0.0)
    prog, _, _ = build_decode_program(cfg, batch=B, cache_len=lc,
                                      width=W, tp_degree=tp)
    np.random.seed(0)
    m = GPTModel(cfg)
    m.eval()
    if weight_dtype == "int8":
        from paddle_tpu.slim.quantization import freeze_weights_int8
        from paddle_tpu.static.executor import Scope
        sd = m.state_dict()
        sc = Scope()
        for pname, key in _param_map(cfg).items():
            sc.set(pname, np.asarray(sd[key].numpy(), np.float32))
        freeze_weights_int8(prog, sc)
    fl = analyze_flops(prog, batch=B)
    fp_flops = fl["total_flops"] - fl.get("int8_flops", 0)
    compute_ms = ((fp_flops + fl.get("int8_flops", 0) / INT8_MXU_RATE)
                  / max(tp, 1) / peak * 1e3)
    # per-layer serial mp wire: ring allreduce moves 2(tp-1)/tp of the
    # [B, W, hidden] activation twice (o-proj + fc2), the two c_concat
    # KV gathers move (tp-1)/tp of it each
    act = B * W * cfg.hidden_size * 4
    frac = (tp - 1) / tp if tp > 1 else 0.0
    wire = cfg.num_layers * (2 * 2 * frac * act + 2 * frac * act)
    wire_serial_ms = wire / ici_bytes_per_chip() * 1e3

    world = 8 if tp > 1 else 1
    places = None if tp > 1 else [jax.devices()[0]]
    dec = TPShardedDecoder(m, tp_degree=tp, places=places,
                           weight_dtype=weight_dtype)
    ids = np.random.randint(0, cfg.vocab_size, (B, W)).astype(np.int64)
    k = np.random.randn(cfg.num_layers, B, cfg.num_heads, lc,
                        cfg.hidden_size // cfg.num_heads)
    k = (k * 0.1).astype(np.float32)
    pos = np.full((B,), lc, np.int64)
    mask = np.zeros((B, 1, W, lc + W), np.float32)

    def cache():
        return [MultiHeadAttention.Cache(paddle_tpu.to_tensor(k[li]),
                                         paddle_tpu.to_tensor(k[li]))
                for li in range(cfg.num_layers)]

    dec.forward(paddle_tpu.to_tensor(ids), cache=cache(),
                pos_offset=pos,
                attn_mask=paddle_tpu.to_tensor(mask))     # warm/compile
    times = []
    for _ in range(3):
        t0 = time.time()
        for _ in range(STEPS):
            out, _ = dec.forward(paddle_tpu.to_tensor(ids), cache=cache(),
                                 pos_offset=pos,
                                 attn_mask=paddle_tpu.to_tensor(mask))
        np.asarray(out.numpy())
        times.append((time.time() - t0) / STEPS)
    return {"label": label, "batch": B, "width": W, "world": world,
            "knobs": {"decode": True, "tp_degree": tp, "cache_len": lc,
                      "weight_dtype": weight_dtype},
            "compute_ms": compute_ms,
            "wire_overlap_ms": 0.0,
            "wire_serial_ms": wire_serial_ms,
            "predicted_raw_ms": compute_ms + wire_serial_ms,
            "measured_ms": round(min(times) * 1e3, 4)}


# (label, batch B, cache_len lc, step width W, tp degree, weight dtype)
# — the serving regime's calibration rows: decode steps from the
# engine's bucket lattice, tp=1 vs tp=2 so the per-world intercepts see
# both mesh classes from the memory-bound side too, plus the int8
# stamped pair of each mesh class so the calibrated roofline carries
# the INT8_MXU_RATE pricing leg
DECODE_SHAPES = [
    ("decode_b4_lc64_w1_tp1", 4, 64, 1, 1, "float32"),
    ("decode_b4_lc64_w1_tp2", 4, 64, 1, 2, "float32"),
    ("decode_b4_lc64_w4_tp2", 4, 64, 4, 2, "float32"),
    ("decode_b4_lc64_w1_int8_tp1", 4, 64, 1, 1, "int8"),
    ("decode_b4_lc64_w1_int8_tp2", 4, 64, 1, 2, "int8"),
]


# (label, width, batch, world, knob spec) — the looped/hoisted gm pair
# shares a rewrite so the hoist's measured win is apples-to-apples
SHAPES = [
    ("fc512_plain_b8", 512, 8, 1, {}),
    ("fc512_plain_b16", 512, 16, 1, {}),
    ("fc512_plain_b32", 512, 32, 1, {}),
    ("fc256_dp8_b16", 256, 16, WORLD, {}),
    ("fc512_dp8_b16", 512, 16, WORLD, {}),
    ("fc256_zero1_b16", 256, 16, WORLD,
     {"dp_shard": WORLD, "zero_stage": 1}),
    ("fc512_zero1_b16", 512, 16, WORLD,
     {"dp_shard": WORLD, "zero_stage": 1}),
    ("fc512_zero2_gm4_b16", 512, 16, WORLD,
     {"dp_shard": WORLD, "zero_stage": 2, "grad_merge": GM_K}),
    ("fc512_zero2_gm4_b16_hoist", 512, 16, WORLD,
     {"dp_shard": WORLD, "zero_stage": 2, "grad_merge": GM_K,
      "scan_hoist": True}),
    ("fc512_zero3_b16", 512, 16, WORLD,
     {"dp_shard": WORLD, "zero_stage": 3}),
]


def run_calibration():
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.static.planner import calibrate

    peak = _host_peak_flops()
    pairs = []
    for label, width, batch, world, spec in SHAPES:
        pred = _predict(width, spec, batch, world, peak)
        measured = _measure(width, spec, batch, world)
        pairs.append(dict(pred, label=label, width=width, batch=batch,
                          world=world, knobs=dict(spec),
                          measured_ms=round(measured, 4)))
    for label, B, lc, W, tp, wdt in DECODE_SHAPES:
        pairs.append(_decode_pair(label, B, lc, W, tp, peak,
                                  weight_dtype=wdt))
    cal = calibrate(pairs)
    return cal, pairs, peak


def render_report(cal, pairs, peak):
    lines = [
        "| shape | compute ms | wire ovl ms | wire ser ms | "
        "raw pred ms | calibrated ms | measured ms | err % |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for p in pairs:
        est = cal.step_ms(p["compute_ms"], p["wire_overlap_ms"],
                          p["wire_serial_ms"])
        err = abs(est - p["measured_ms"]) / p["measured_ms"] * 100
        lines.append(
            "| {label} | {compute_ms:.4f} | {wire_overlap_ms:.4f} | "
            "{wire_serial_ms:.4f} | {predicted_raw_ms:.4f} | "
            "{est:.3f} | {measured_ms:.3f} | {err:.1f} |".format(
                est=est, err=err, **p))
    lines.append("")
    lines.append(
        f"Fit: eff_compute={cal.eff_compute:.4f}, "
        f"eff_wire_overlap={cal.eff_wire_overlap:.4f}, "
        f"eff_wire_serial={cal.eff_wire_serial:.4f}, "
        f"overhead_ms={cal.overhead_ms:.3f}; "
        f"mean |err| = {cal.residual_pct:.1f}% over {cal.n_pairs} "
        f"shapes (host matmul rate {peak / 1e9:.1f} GFLOP/s).")
    return "\n".join(lines)


def main():
    out_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "perf_r05", "roofline_calibration.json")
    if "--out" in sys.argv:
        out_path = sys.argv[sys.argv.index("--out") + 1]
    t0 = time.time()
    cal, pairs, peak = run_calibration()
    wall = time.time() - t0
    cal.save(out_path, extra={
        "tool": "tools/calibrate_roofline.py",
        "host_platform": "cpu",
        "host_peak_flops": round(peak, 1),
        "world": WORLD,
        "pairs": pairs,
    })
    if "--report" in sys.argv:
        print(render_report(cal, pairs, peak))
    print(json.dumps({
        "metric": "roofline_calibration_residual_pct",
        "value": round(cal.residual_pct, 2),
        "coefficients": cal.to_dict(),
        "n_pairs": cal.n_pairs,
        "out": out_path,
        "wall_s": round(wall, 1),
    }))


if __name__ == "__main__":
    main()

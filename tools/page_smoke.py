"""Fast CPU paged-KV gate: planner-sized pool, COW prefix sharing,
token-equal paged decode, zero post-warmup retraces.

The cheap canary for the serving tier's paged KV cache
(tests/test_page_smoke.py runs it as a tier-1 test, mirroring
mem_smoke/serve_smoke): sizes a ``PagedKVPool`` with
``static.page_budget`` (the HBM-walker path — never a hand-set page
count), then asserts the contracts the paged engine rests on:

  * the pool allocates exactly the planner-chosen budget and
    ``budget_drift`` re-derives it clean (V504-style detectability);
  * two live prompts sharing a head occupy FEWER pages than 2x solo
    (refcounted prefix pages), and a decode write into a shared page
    copies first (COW isolation);
  * greedy decode through the paged ContinuousBatchingEngine is
    token-equal to per-sequence ``generate()`` across admit/retire
    churn;
  * the padded KV-length buckets the model compiles against stop
    growing after warmup (paging must not leak page structure into
    compiled shapes), and the drained pool holds zero pages.

Prints one JSON line; correctness never depends on throughput.

Usage: python tools/page_smoke.py
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the planner budget the gate sizes against: small enough that the pool
# slab is a few hundred KB of host numpy, big enough for the churn run
SMOKE_HBM_BYTES = 4 * 1024 * 1024


def run_smoke():
    """Run the gate; returns the result dict (AssertionError on any
    paged-KV contract regression)."""
    os.environ.setdefault("PADDLE_TPU_VERIFY", "warn")
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.dygraph as dg
    from paddle_tpu.models import GPTConfig, GPTModel, GPTForGeneration
    from paddle_tpu.serving import (ContinuousBatchingEngine, PagedKVPool,
                                    budget_drift)
    from paddle_tpu.static import page_budget

    t0 = time.time()
    rng = np.random.RandomState(11)
    with dg.guard():
        cfg = GPTConfig(vocab_size=48, hidden_size=16, num_layers=2,
                        num_heads=2, max_position=64, dropout=0.0)
        m = GPTForGeneration(GPTModel(cfg))
        m.eval()

        # -- planner-sized pool: budget chosen by the HBM walker path --
        plan = page_budget(m, page_tokens=4,
                           hbm_bytes=SMOKE_HBM_BYTES)
        pool = PagedKVPool.from_plan(plan)
        assert pool.num_pages == plan["pages"], \
            f"pool ignored the plan: {pool.num_pages} != {plan['pages']}"
        assert pool.k.nbytes + pool.v.nbytes == plan["kv_bytes"], \
            "allocated slab disagrees with the plan's kv_bytes"
        drift = budget_drift(pool, m)
        assert drift == [], f"fresh plan-built pool drifts: {drift}"

        # -- prefix sharing: two sharers < 2x solo ---------------------
        head = rng.randint(2, 48, (8,)).astype(np.int64)  # 2 full pages
        pa = np.concatenate([head, [3]])
        pb = np.concatenate([head, [5]])
        solo = pool.pages_needed(pa.size)
        L, H = plan["num_layers"], plan["num_heads"]
        k = rng.randn(L, H, pa.size, plan["head_dim"]).astype(np.float32)
        v = rng.randn(L, H, pa.size, plan["head_dim"]).astype(np.float32)
        ta = pool.open_sequence(pa, k, v)
        tb = pool.open_sequence(pb, k, v)
        shared_used = pool.num_pages - pool.pages_free
        assert shared_used < 2 * solo, \
            f"sharing saved nothing: {shared_used} pages for 2 prompts " \
            f"vs {solo} solo"
        prefix_hits = pool.prefix_hits
        assert prefix_hits == 2, f"expected 2 head-page hits, " \
                                 f"got {prefix_hits}"
        # COW: an IDENTICAL prompt shares every page including the
        # partial tail page; its first decode write must copy that page,
        # leaving ta's view bitwise intact
        tc = pool.open_sequence(pa, k, v)
        assert pool.prefix_hits == prefix_hits + 3
        col = rng.randn(L, H, plan["head_dim"]).astype(np.float32)
        pool.append_column(tc, col, col)
        assert pool.cow_copies == 1, "shared-page write did not copy"
        ka, _ = pool.gather(ta)
        np.testing.assert_array_equal(ka, k)
        pool.close_sequence(ta)
        pool.close_sequence(tb)
        pool.close_sequence(tc)
        pool.assert_drained()

        # -- token-equal paged decode across admit/retire churn --------
        prompts = [rng.randint(2, 48, (n,)).astype(np.int64)
                   for n in (3, 6, 2)]
        prompts += [np.concatenate([head, [7]]),
                    np.concatenate([head, [9]])]
        refs = [np.asarray(m.generate(p[None], max_length=5,
                                      decode_strategy="greedy_search")[0])
                for p in prompts]
        eng = ContinuousBatchingEngine(m, max_slots=2,
                                       kv_pool=pool).start()
        try:
            # warmup: one request exercises the prefill/decode buckets
            eng.submit(prompts[0], max_length=5).result(timeout=60)
            warm_buckets = eng.kv_buckets
            futs = [eng.submit(p, max_length=5) for p in prompts]
            outs = [np.asarray(f.result(timeout=60)) for f in futs]
            buckets_after = eng.kv_buckets
        finally:
            eng.stop()
        for ref, out in zip(refs, outs):
            np.testing.assert_array_equal(ref, out)
        retraces = buckets_after - warm_buckets
        assert retraces == 0, \
            f"{retraces} new compiled KV buckets after warmup — paging " \
            f"leaked page structure into compiled shapes"
        pool.assert_drained()               # zero pages leaked post-drain

    wall = time.time() - t0
    result = {
        "metric": "page_smoke_wall_s",
        "value": round(wall, 2),
        "unit": "s",
        "pages": plan["pages"],
        "page_tokens": plan["page_tokens"],
        "max_slots": plan["max_slots"],
        "max_context": plan["max_context"],
        "kv_bytes": plan["kv_bytes"],
        "solo_pages": solo,
        "shared_pages_for_two": shared_used,
        "prefix_hits": prefix_hits,
        "cow_copies": 1,
        "sequences_token_equal": len(prompts),
        "traces_after_warmup": retraces,
    }
    return result


def main():
    result = run_smoke()
    print(json.dumps(result))


if __name__ == "__main__":
    main()

"""Fleet gate: a two-host elastic fleet survives losing a host, bitwise.

The tier-1 slice of the fleet control plane (tests/test_fleet_smoke.py
runs it; docs/elastic.md "Cross-host fleets").  Two REAL launcher
processes (``paddle_tpu.distributed.launch --elastic --fleet_dir``)
simulate two hosts of a logical-8 fleet on the 8-device CPU mesh, each
supervising one trainer (tools/fleet_worker.py) that owns 4 of the
logical chips:

  1. both launchers rendezvous at the shared fleet dir, agree the
     epoch-0 formation (members {0,1}, world 8) and spawn trainers;
     trainers publish SHARED rank-sharded checkpoints through the fleet
     barrier (save → wait → cross-host barrier → rank-0 commit);
  2. chaos takes host 1 down WHOLE (``lose_host@4:host=1`` SIGKILLs
     launcher + trainer after global step 2 — no goodbye); host 0's
     next publish barrier can never pass, so its trainer stalls at the
     exact committed frontier;
  3. host 0's controller sees host 1's membership go stale, tears its
     pod down (SIGTERM — the preemption save stages), runs the
     two-phase survivor agreement — members {0}, world 4, restore step
     picked LIVE from the run journals (newest step every survivor
     staged AND some rank committed) — and relaunches;
  4. the relaunched trainer's world_size=1 manager hits the world-of-2
     checkpoint and loads it RANK-MERGED (CheckpointManager.
     load_merged), resumes at the agreed step, and finishes;
  5. the survivor's stitched loss trace and final params must be
     BITWISE equal to an uninterrupted single-process 8-device run
     (the ROADMAP Done= condition), and the journals must show the
     reform + merged restore.

Prints one JSON line; correctness never depends on throughput.

Usage: python tools/fleet_smoke.py [--steps 4] [--kill-at 2]
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LOGICAL = 8
N_HOSTS = 2
CAPACITY = 4  # logical chips per host


def _reference(steps):
    """Uninterrupted 8-device elastic run: the bitwise oracle."""
    import jax
    import paddle_tpu.static as static
    from paddle_tpu.distributed.compiled_program import CompiledProgram
    from paddle_tpu.distributed.elastic import rebucket_feeds
    from tools.fleet_worker import build_elastic, feeds_for
    main, startup, loss, meta = build_elastic()
    exe = static.Executor()
    scope = static.Scope()
    trace = []
    with static.scope_guard(scope):
        exe.run(startup)
        cp = CompiledProgram(main).with_data_parallel(
            loss_name=loss.name, places=list(jax.devices())[:LOGICAL])
        for f in feeds_for(steps):
            for mf in rebucket_feeds(f, LOGICAL, LOGICAL):
                out = exe.run(cp, feed=mf, fetch_list=[meta["loss_avg"]])
            trace.append(float(np.asarray(out[0]).reshape(-1)[0]))
        params = {p.name: np.asarray(scope.get(p.name)).tolist()
                  for p in main.all_parameters()}
    return trace, params


def run_smoke(steps: int = 4, kill_at: int = 2, base: str = None):
    """Run the gate; returns the result dict (AssertionError on a fleet
    re-form / rank-merged-restore regression)."""
    # every tier-1 smoke doubles as a verifier sweep
    os.environ.setdefault("PADDLE_TPU_VERIFY", "warn")
    import jax
    jax.config.update("jax_platforms", "cpu")
    assert 0 < kill_at < steps
    ndev = len(jax.devices())
    assert ndev >= LOGICAL, (
        f"fleet smoke needs {LOGICAL} devices "
        f"(XLA_FLAGS=--xla_force_host_platform_device_count={LOGICAL}), "
        f"got {ndev}")
    t_start = time.time()
    base = base or tempfile.mkdtemp(prefix="fleet_smoke_")
    fleet_dir = os.path.join(base, "fleet")
    journal_dir = os.path.join(base, "journal")
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "fleet_worker.py")
    ref_trace, ref_params = _reference(steps)

    # K = LOGICAL/CAPACITY micro-runs per global step on a host mesh
    kill_run = kill_at * (LOGICAL // CAPACITY)
    launchers = []
    for host in range(N_HOSTS):
        env = dict(os.environ)
        env.update({
            "PADDLE_TPU_FLEET_TEST_DIR": base,
            "FLEET_TOTAL_STEPS": str(steps),
            "PADDLE_TPU_CHAOS": f"lose_host@{kill_run}:host=1",
            "JAX_PLATFORMS": "cpu",
        })
        log = open(os.path.join(base, f"launcher{host}.log"), "w")
        cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
               "--elastic", "--fleet_dir", fleet_dir,
               "--ips", "127.0.0.1,127.0.0.1",
               "--host_rank", str(host),
               "--host_capacity", str(CAPACITY),
               "--member_timeout", "2.5",
               "--term_grace", "5",
               "--journal_dir", journal_dir,
               worker]
        launchers.append((host, subprocess.Popen(
            cmd, env=env, stdout=log, stderr=log), log))

    rcs = {}
    deadline = time.time() + 120
    for host, proc, log in launchers:
        try:
            rcs[host] = proc.wait(max(1.0, deadline - time.time()))
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(10)
            rcs[host] = "timeout"
        log.close()

    def _log(host):
        try:
            with open(os.path.join(base, f"launcher{host}.log")) as f:
                return f.read()[-3000:]
        except OSError:
            return "<no log>"

    assert rcs[0] == 0, (
        f"fleet smoke FAILED: survivor launcher exited {rcs[0]}\n"
        f"{_log(0)}")
    assert rcs[1] != 0, (
        "fleet smoke FAILED: the chaos-killed host's launcher exited 0 "
        "— lose_host never fired?\n" + _log(1))

    # -- the survivors agreed on ONE re-formed world ------------------------
    from paddle_tpu.distributed.fleet_control import read_commit
    commit0 = read_commit(fleet_dir, 0)
    commit1 = read_commit(fleet_dir, 1)
    assert commit0 is not None and commit0.members == [0, 1] \
        and commit0.world == LOGICAL, commit0
    assert commit1 is not None, "no epoch-1 commit: re-form never agreed"
    assert commit1.members == [0] and commit1.world == CAPACITY, commit1
    assert commit1.restore_step is not None, (
        "re-form carried no restore step (journal agreement failed)")

    # -- stitched survivor trace + final params BITWISE-equal ---------------
    with open(os.path.join(base, "out_host0_e1.json")) as f:
        final = json.load(f)
    assert final["done"], "relaunched trainer never completed"
    assert final["resumed_global"] == kill_at, final["resumed_global"]
    with open(os.path.join(base, "out_host0_e0.json")) as f:
        phase1 = json.load(f)
    stitched = dict(phase1["losses"])
    stitched.update(final["losses"])
    for gi in range(steps):
        got = stitched.get(str(gi), stitched.get(gi))
        assert got is not None, f"global step {gi} missing from traces"
        assert got == ref_trace[gi], (
            f"fleet smoke FAILED: loss trace diverged at global step "
            f"{gi}: {got!r} != {ref_trace[gi]!r}")
    for name, want in ref_params.items():
        got = final["params"][name]
        assert np.array_equal(np.asarray(want), np.asarray(got)), (
            f"fleet smoke FAILED: param {name} diverged after the "
            "rank-merged fleet restore")

    # -- journals show the reform + the merged restore ----------------------
    from paddle_tpu.observability.journal import (read_rank_journals,
                                                  reconstruct_timeline)
    journals = read_rank_journals(journal_dir)
    reforms = [r for events in journals.values()
               for inc in reconstruct_timeline(events)["incarnations"]
               for r in inc["reforms"]]
    assert any(r["epoch"] == 1 and r["world"] == CAPACITY
               for r in reforms), f"no reform event journaled: {reforms}"
    merged = [e for events in journals.values() for e in events
              if e.get("kind") == "restore_merged"]
    assert merged and merged[0]["merged_from_world"] == N_HOSTS, (
        "survivor did not restore through the rank-merged loader")

    return {
        "metric": "fleet_smoke_reformed_world",
        "value": commit1.world,
        "logical_dp": LOGICAL,
        "hosts": N_HOSTS,
        "kill_at_global_step": kill_at,
        "restore_step": commit1.restore_step,
        "global_steps": steps,
        "bitwise_loss_trace": True,
        "bitwise_params": True,
        "reform_epochs": 1,
        "wall_s": round(time.time() - t_start, 2),
    }


def main():
    steps, kill_at = 4, 2
    if "--steps" in sys.argv:
        steps = int(sys.argv[sys.argv.index("--steps") + 1])
    if "--kill-at" in sys.argv:
        kill_at = int(sys.argv[sys.argv.index("--kill-at") + 1])
    print(json.dumps(run_smoke(steps=steps, kill_at=kill_at)))


if __name__ == "__main__":
    sys.exit(main())

"""Regenerate the docs/perf.md decision table from the auto-parallel
planner (static/planner.py) — the ISSUE 10 "self-serve instead of
reviewer-tuned" loop closure.

For every row of the hand-tuned decision table (the five BASELINE
shapes — LeNet / ResNet-50 / Transformer-big / BERT-base / ERNIE-large
— at their recorded batches, plus the bert batch ladder the r5/r6
rounds measured) this tool:

  1. builds the shape's training program with the repo's own builders,
  2. runs `static.plan_program` over the knob lattice (the HAND-chosen
     knob point is always injected into the lattice so the comparison
     is apples-to-apples),
  3. prints planner knobs + predicted peak / fits / step time next to
     the hand verdict's priced record, and FAILS (exit 1) if the
     planner's choice is slower than the hand row or does not fit where
     the hand row fits — the ISSUE 10 acceptance gate.

Output: a markdown table for docs/perf.md (stdout) and, with --queue,
`perf_r05/queue.txt`-format lines for the planner-chosen configs of the
five BASELINE shapes (the next tunnel window's `bench.py --auto` runs).

Usage:
    python tools/plan_decision_table.py [--rows bert,ernie,...] [--queue]
        [--fast]   # skip per-candidate verification (pricing only)
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _build_bert(batch, seq=512, layers_n=12, hidden=768, heads=12,
                vocab=30522, ring=False):
    import bench
    from paddle_tpu.core.program import _reset_unique_names
    _reset_unique_names()
    main, startup, _ = bench.build_bert_base(
        vocab, seq, hidden, layers_n, heads, batch, use_amp=True,
        use_ring=ring)
    return main, startup


def _build_ernie_large(batch):
    return _build_bert(batch, layers_n=24, hidden=1024, heads=16)


def _build_lenet(batch):
    import bench_lenet
    from paddle_tpu.core.program import _reset_unique_names
    _reset_unique_names()
    main, startup, _ = bench_lenet.build_lenet()
    return main, startup


def _build_resnet(batch):
    import bench_resnet
    from paddle_tpu.core.program import _reset_unique_names
    _reset_unique_names()
    main, startup = bench_resnet.build_resnet50(batch)[:2]
    return main, startup


def _build_transformer(batch):
    import bench_transformer
    from paddle_tpu.core.program import _reset_unique_names
    _reset_unique_names()
    out = bench_transformer.build_transformer_big(256, 256)
    return out[0], out[1]


# the tp row's geometry: the planner auto-generates dp×tp variants from
# this config (tensor_parallel builders), so the tp column is searched,
# never hand-fed
LM_TP_CONFIG = dict(vocab_size=1024, hidden=256, num_layers=4,
                    num_heads=8, seq_len=128, learning_rate=1e-4)


def _build_lm_tp_base(batch):
    import paddle_tpu.static as static
    from paddle_tpu.core.program import _reset_unique_names
    from paddle_tpu.models import build_transformer_lm
    _reset_unique_names()
    main, startup, loss, _ = build_transformer_lm(
        vocab_size=LM_TP_CONFIG["vocab_size"],
        hidden=LM_TP_CONFIG["hidden"],
        num_layers=LM_TP_CONFIG["num_layers"],
        num_heads=LM_TP_CONFIG["num_heads"],
        seq_len=LM_TP_CONFIG["seq_len"])
    with static.program_guard(main, startup):
        static.Adam(
            learning_rate=LM_TP_CONFIG["learning_rate"]).minimize(loss)
    return main, startup


# (row key, label, builder, batch, world, hand knobs, hand-fits)
# Hand column = the human-tuned docs/perf.md verdicts (r5 on-chip ground
# truth where measured) kept as the cross-check.
ROWS = [
    ("lenet", "LeNet b256", _build_lenet, 256, 1,
     dict(remat=False, dp_shard=0, zero_stage=0, grad_merge=1,
          ring=False), True),
    ("resnet", "ResNet-50 b128", _build_resnet, 128, 1,
     dict(remat=False, dp_shard=0, zero_stage=0, grad_merge=1,
          ring=False), True),
    ("transformer", "Transformer-big s256 b16", _build_transformer, 16, 1,
     dict(remat=False, dp_shard=0, zero_stage=0, grad_merge=1,
          ring=False), True),
    ("bert32", "bert-base b32", _build_bert, 32, 1,
     dict(remat=False, dp_shard=0, zero_stage=0, grad_merge=1,
          ring=False), True),
    ("bert64", "bert-base b64", _build_bert, 64, 1,
     dict(remat=False, dp_shard=0, zero_stage=0, grad_merge=1,
          ring=False), True),
    ("bert96", "bert-base b96", _build_bert, 96, 1,
     dict(remat=True, dp_shard=0, zero_stage=0, grad_merge=1,
          ring=False), True),
    ("bert128", "bert-base b128 (N=8)", _build_bert, 128, 8,
     dict(remat=True, dp_shard=8, zero_stage=1, grad_merge=1,
          ring=False), True),
    ("ernie16", "ERNIE-large b16", _build_ernie_large, 16, 1,
     dict(remat=False, dp_shard=0, zero_stage=0, grad_merge=1,
          ring=False), True),
    ("ernie24", "ERNIE-large b24 (N=8)", _build_ernie_large, 24, 8,
     dict(remat=False, dp_shard=8, zero_stage=1, grad_merge=1,
          ring=False), True),
    # the tp column: the hand verdict is a hand-built 4×2 dp×tp config
    # (the PR-12 acceptance mesh); the planner searches the auto-
    # generated tp variants and must tie or beat it — on this
    # comfortably-fitting shape the honest answer is pure dp (no mp
    # wire), which beats the hand 2-D point
    ("lm_tp", "transformer-lm h256 s128 (N=8, dp×tp searched)",
     _build_lm_tp_base, 16, 8,
     dict(remat=False, dp_shard=0, zero_stage=0, grad_merge=1,
          ring=False, tp_degree=2), True),
]

# per-row model configs that put auto-generated tp variants on the
# lattice (rows absent here search the classic 1-D axes only)
ROW_CONFIGS = {"lm_tp": LM_TP_CONFIG}

# queue lines for the planner-chosen configs that actually exercise the
# plan→apply→run path (bench.py --auto).  The planner chose PLAIN for
# LeNet / ResNet-50 / Transformer-big, and those plain configs are
# already queued as the lenet/resnet_b128/transformer_b16 baseline
# runs — re-queuing them under an auto_ label would burn tunnel time on
# duplicate measurements falsely attributed to the planner.
QUEUE_CMDS = {
    "bert64": "auto_bert_base|BENCH_AUTO_TPU=1 BENCH_WORLD=1 "
              "python bench.py --auto",
    "ernie24": "auto_ernie_large_b24|BENCH_AUTO_TPU=1 BENCH_LAYERS=24 "
               "BENCH_HIDDEN=1024 BENCH_HEADS=16 BENCH_BATCH=24 "
               "python bench.py --auto",
}


def _fmt_knobs(k):
    parts = []
    if k.get("remat"):
        parts.append("remat")
    if k.get("dp_shard"):
        parts.append(f"zero{k.get('zero_stage') or 1}/{k['dp_shard']}")
    if int(k.get("grad_merge") or 1) > 1:
        parts.append(f"gm{k['grad_merge']}")
    if k.get("ring"):
        parts.append("ring")
    if int(k.get("tp_degree") or 0) > 1:
        parts.append(f"tp{k['tp_degree']}")
    return "+".join(parts) or "plain"


def main():
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.static as static

    want = None
    if "--rows" in sys.argv:
        want = set(sys.argv[sys.argv.index("--rows") + 1].split(","))
    verify = "--fast" not in sys.argv
    emit_queue = "--queue" in sys.argv

    lines = ["| config | planner choice | planned peak | fits | "
             "pred. step ms | hand verdict (cross-check) | "
             "planner ≤ hand? |",
             "|---|---|---|---|---|---|---|"]
    queue_lines, failures = [], []
    for key, label, builder, batch, world, hand, hand_fits in ROWS:
        if want and key not in want:
            continue
        t0 = time.time()
        main_p, startup_p = builder(batch)
        # inject the hand point into the lattice so it is always priced
        knobs = {
            "remat": (False, True),
            "dp_shard": tuple(sorted({0, world if world > 1 else 0,
                                      hand["dp_shard"]})),
            "grad_merge": tuple(sorted({1, hand["grad_merge"]})),
        }
        model_config = ROW_CONFIGS.get(key)
        if model_config is not None:
            knobs["tp_degree"] = tuple(sorted(
                {0, int(hand.get("tp_degree") or 0)} | {0, 2}))
        plan = static.plan_program(main_p, startup_p, world=world,
                                   batch=batch, knobs=knobs,
                                   model_config=model_config,
                                   verify=verify)
        hand_rec = next(
            (c for c in plan.trace
             if c["remat"] == hand["remat"]
             and c["dp_shard"] == hand["dp_shard"]
             and c["zero_stage"] == hand.get("zero_stage",
                                             1 if hand["dp_shard"] else 0)
             and c["grad_merge"] == hand["grad_merge"]
             and c["ring"] == hand["ring"]
             and int(c.get("tp_degree") or 0) ==
             int(hand.get("tp_degree") or 0)), None)
        beat = (plan.predicted_fits and hand_rec is not None and
                plan.predicted_step_ms <= hand_rec["step_ms"] + 1e-9)
        if hand_fits and not beat:
            failures.append(label)
        hand_txt = "?" if hand_rec is None else (
            f"{_fmt_knobs(hand)} — {hand_rec['peak_bytes'] / 2**30:.1f} "
            f"GiB, {'fits' if hand_rec['fits'] else 'OOM'}, "
            f"{hand_rec['step_ms']:.2f} ms")
        lines.append(
            f"| {label} | {_fmt_knobs(plan.knobs)} | "
            f"{plan.predicted_peak_bytes / 2**30:.1f} GiB | "
            f"{'yes' if plan.predicted_fits else 'no'} | "
            f"{plan.predicted_step_ms:.2f} | {hand_txt} | "
            f"{'yes' if beat else 'NO'} |")
        if key in QUEUE_CMDS:
            queue_lines.append(QUEUE_CMDS[key])
        sys.stderr.write(
            f"{key}: planned in {time.time() - t0:.1f}s -> "
            f"{_fmt_knobs(plan.knobs)} "
            f"({json.dumps(plan.to_dict()['knobs'])})\n")

    print("\n".join(lines))
    if emit_queue:
        print("\n# queue lines (perf_r05/queue.txt):")
        for ln in queue_lines:
            print(ln)
    if failures:
        sys.stderr.write(
            f"FAILED: planner worse than hand verdict on: {failures}\n")
        sys.exit(1)


if __name__ == "__main__":
    main()

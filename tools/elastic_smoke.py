"""Elastic gate: kill at full world, resume on a SHRUNK mesh, same math.

The tier-1 slice of the elastic tier (tests/test_elastic_smoke.py runs
it, budgeted <25 s wall on the 8-device CPU mesh; the full chaos-driven
8→4→8 kill/shrink/regrow matrix lives in tests/test_elastic.py marked
``slow``).  Single process, one shrink:

  1. elasticize a tiny model for logical_dp=8 and train ``kill_at``
     global steps on the full 8-device mesh with per-step async
     checkpointing;
  2. "lose half the fleet": fresh executor/scope/manager (process-
     restart semantics), ``restore_from_checkpoint(world=4)`` — the
     topology-shifted restore re-derives the micro-step counter and
     schedule position for K=2;
  3. train the remaining global steps on the 4-device mesh, feeding the
     SAME global batches re-bucketed into K=2 micro-feeds;
  4. assert the loss trace and final params are BITWISE equal to an
     uninterrupted 8-device run — the world-size-invariant ordered fold
     (c_elastic_fold) makes the reduction order a property of the
     program, not the mesh.

Prints one JSON line; correctness never depends on throughput.

Usage: python tools/elastic_smoke.py [--steps 4] [--kill-at 2]
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LOGICAL = 8
SHRUNK = 4


def build_elastic():
    import paddle_tpu.static as static
    from paddle_tpu.static import layers
    from paddle_tpu.core.program import _reset_unique_names
    from paddle_tpu.distributed.elastic import elasticize
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        h = layers.fc(x, 16, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.Adam(learning_rate=1e-2).minimize(loss)
    meta = elasticize(main, startup, logical_dp=LOGICAL, loss_name=loss)
    return main, startup, loss, meta


def _train(exe, scope, main, loss, meta, world, feeds):
    import jax
    import paddle_tpu.static as static
    from paddle_tpu.distributed.compiled_program import CompiledProgram
    from paddle_tpu.distributed.elastic import rebucket_feeds
    cp = CompiledProgram(main).with_data_parallel(
        loss_name=loss.name, places=list(jax.devices())[:world])
    trace = []
    with static.scope_guard(scope):
        for f in feeds:
            for mf in rebucket_feeds(f, LOGICAL, world):
                out = exe.run(cp, feed=mf, fetch_list=[meta["loss_avg"]])
            trace.append(np.asarray(out[0]))
    return trace


def run_smoke(steps: int = 4, kill_at: int = 2, root: str = None):
    """Run the gate; returns the result dict (AssertionError on an
    elastic-resume regression)."""
    # every tier-1 smoke doubles as a verifier sweep (ISSUE 10):
    # armed here, the first-compile hook and the rewrite-pass
    # self-checks verify every program this gate builds, for free
    os.environ.setdefault("PADDLE_TPU_VERIFY", "warn")
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.static as static
    from paddle_tpu.checkpoint import CheckpointManager

    t_start = time.time()
    assert 0 < kill_at < steps
    ndev = len(jax.devices())
    assert ndev >= LOGICAL, (
        f"elastic smoke needs {LOGICAL} devices "
        f"(XLA_FLAGS=--xla_force_host_platform_device_count={LOGICAL}), "
        f"got {ndev}")
    root = root or tempfile.mkdtemp(prefix="elastic_smoke_")
    rng = np.random.RandomState(0)
    feeds = [{"x": rng.rand(LOGICAL, 8).astype(np.float32),
              "y": rng.rand(LOGICAL, 1).astype(np.float32)}
             for _ in range(steps)]

    # uninterrupted full-world reference
    main, startup, loss, meta = build_elastic()
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
    t0 = time.time()
    ref = _train(exe, scope, main, loss, meta, LOGICAL, feeds)
    full_compile_s = time.time() - t0
    with static.scope_guard(scope):
        ref_params = {p.name: np.asarray(scope.get(p.name))
                      for p in main.all_parameters()}

    # phase 1: full world with per-global-step checkpoints, "killed"
    main1, startup1, loss1, meta1 = build_elastic()
    exe1 = static.Executor()
    scope1 = static.Scope()
    mgr = CheckpointManager(root)
    with static.scope_guard(scope1):
        exe1.run(startup1)
        exe1.enable_checkpointing(mgr, program=main1, every_n_steps=1,
                                  scope=scope1)
    _train(exe1, scope1, main1, loss1, meta1, LOGICAL, feeds[:kill_at])
    mgr.close()

    # phase 2: half the fleet is gone — resume on a 4-device mesh.
    # Deliberately NO world= hint: the restore re-derives for its
    # default (all local devices) and the first CompiledProgram run
    # re-anchors counter/step for the ACTUAL 4-device mesh — the
    # world-mismatch path a real resume takes when the job script
    # learns its mesh after restoring.
    main2, startup2, loss2, meta2 = build_elastic()
    exe2 = static.Executor()
    scope2 = static.Scope()
    mgr2 = CheckpointManager(root)
    with static.scope_guard(scope2):
        exe2.run(startup2)
        resumed = exe2.restore_from_checkpoint(
            mgr2, program=main2, scope=scope2)
    assert resumed is not None, "elastic smoke FAILED: nothing to resume"
    g = exe2.last_restored_extra.get("global_step")
    assert g == kill_at, (
        f"elastic smoke FAILED: re-derived global step {g}, "
        f"expected {kill_at}")
    trace2 = _train(exe2, scope2, main2, loss2, meta2, SHRUNK, feeds[g:])
    mgr2.close()

    # the shrunk continuation must be BITWISE the uninterrupted trace
    for i, (a, b) in enumerate(zip(ref[g:], trace2)):
        assert np.array_equal(a, b), (
            f"elastic smoke FAILED: loss trace diverged at global step "
            f"{g + i}: {a!r} != {b!r} (8-dev reference vs 4-dev resume)")
    with static.scope_guard(scope2):
        for name, want in ref_params.items():
            got = np.asarray(scope2.get(name))
            assert np.array_equal(want, got), (
                f"elastic smoke FAILED: param {name} diverged after "
                "topology-shifted resume")

    result = {
        "metric": "elastic_smoke_resume_world",
        "value": SHRUNK,
        "logical_dp": LOGICAL,
        "kill_at_global_step": kill_at,
        "resumed_checkpoint_step": resumed,
        "global_steps": steps,
        "bitwise_loss_trace": True,
        "bitwise_params": True,
        "full_world_phase_s": round(full_compile_s, 2),
        "wall_s": round(time.time() - t_start, 2),
    }
    return result


def main():
    steps, kill_at = 4, 2
    if "--steps" in sys.argv:
        steps = int(sys.argv[sys.argv.index("--steps") + 1])
    if "--kill-at" in sys.argv:
        kill_at = int(sys.argv[sys.argv.index("--kill-at") + 1])
    print(json.dumps(run_smoke(steps=steps, kill_at=kill_at)))


if __name__ == "__main__":
    main()

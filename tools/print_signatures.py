"""Public-API signature dump (reference: tools/print_signatures.py, used
by tools/check_api_approvals.sh to freeze the API surface).

Prints one `module.symbol(signature)` line per public callable of the
curated module list; `tests/test_api_signatures.py` diffs this against the
checked-in snapshot so accidental API breaks fail CI.  Regenerate after an
INTENTIONAL change with:

    python tools/print_signatures.py > tests/api_signatures.txt
"""
from __future__ import annotations

import inspect
import sys

MODULES = [
    "paddle_tpu",
    "paddle_tpu.static",
    "paddle_tpu.nn",
    "paddle_tpu.nn.functional",
    "paddle_tpu.tensor",
    "paddle_tpu.optimizer",
    "paddle_tpu.io",
    "paddle_tpu.jit",
    "paddle_tpu.amp",
    "paddle_tpu.metric",
    "paddle_tpu.distributed",
    "paddle_tpu.distributed.fleet",
    "paddle_tpu.distributed.fleet_control",
    "paddle_tpu.distributed.tensor_parallel",
    "paddle_tpu.inference",
    "paddle_tpu.serving",
    "paddle_tpu.checkpoint",
    "paddle_tpu.observability",
    "paddle_tpu.slim",
    "paddle_tpu.incubate",
]


def _sig(obj) -> str:
    try:
        return str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(...)"


def iter_api():
    import importlib
    for mod_name in MODULES:
        mod = importlib.import_module(mod_name)
        public = getattr(mod, "__all__", None)
        if public is None:
            public = [n for n in dir(mod) if not n.startswith("_")]
        for name in sorted(set(public)):
            obj = getattr(mod, name, None)
            if obj is None or inspect.ismodule(obj):
                continue
            if inspect.isclass(obj):
                yield f"{mod_name}.{name}{_sig(obj.__init__)}"
                for m_name, m in sorted(vars(obj).items()):
                    if m_name.startswith("_") or not callable(m):
                        continue
                    yield f"{mod_name}.{name}.{m_name}{_sig(m)}"
            elif callable(obj):
                yield f"{mod_name}.{name}{_sig(obj)}"


def main():
    for line in sorted(set(iter_api())):
        sys.stdout.write(line + "\n")


if __name__ == "__main__":
    main()

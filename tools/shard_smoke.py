"""Fast CPU ZeRO sharding gate: rewrite applies, shard shapes correct,
zero post-warmup retraces, estimator shows the slot/param reduction.

The cheap canary for the sharded data-parallel tier
(tests/test_shard_smoke.py runs it as a tier-1 test, mirroring
mem_smoke/ckpt_smoke): builds a small Adam model, applies
`shard_optimizer_states` for the 8-device CPU mesh, and asserts the
contracts the tier rests on:

  * the rewrite actually applied — per-param optimizer ops collapsed
    into bucketed c_reducescatter → sharded update → c_allgather chains;
  * shard shapes are correct — bucket slots declared at the padded
    global length, divisible by the dp world, marked ``dp_shard``, and
    on-mesh each rank materializes exactly 1/world of the slot;
  * the HBM estimator's world-size accounting reports the slot
    reduction (≤ plain/world + one bucket of padding);
  * the compile-once contract holds — a short mesh training run compiles
    ONE executable and never re-traces after warmup;
  * the ZeRO-3 leg: full parameter sharding packs the params into
    dp_shard buckets at ~1/world per chip, just-in-time allgathers are
    present in forward (and the stage-1 publish is gone), a short mesh
    run trains finite with zero post-warmup retraces.

Prints one JSON line; correctness never depends on throughput.

Usage: python tools/shard_smoke.py [--steps 4]
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

WORLD = 8


def run_smoke(steps: int = 4, batch: int = 16):
    """Run the gate; returns the result dict (AssertionError on a
    sharding or retrace regression)."""
    # every tier-1 smoke doubles as a verifier sweep (ISSUE 10):
    # armed here, the first-compile hook and the rewrite-pass
    # self-checks verify every program this gate builds, for free
    os.environ.setdefault("PADDLE_TPU_VERIFY", "warn")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={WORLD}"
        ).strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.static as static
    from paddle_tpu.static import layers
    from paddle_tpu.core.program import _reset_unique_names
    from paddle_tpu.distributed.compiled_program import CompiledProgram
    from paddle_tpu.distributed.sharding import shard_optimizer_states

    t0 = time.time()
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 16])
        y = layers.data("y", [-1, 1])
        h = layers.fc(x, 32, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.Adam(learning_rate=1e-2).minimize(loss)

    plain = static.analyze_program(main, batch=batch)
    n_adam_before = sum(1 for op in main.global_block().ops
                        if op.type == "adam")
    plan = shard_optimizer_states(main, startup, dp_degree=WORLD)
    sharded = static.analyze_program(main, batch=batch)

    # -- rewrite applied ----------------------------------------------------
    types = [op.type for op in main.global_block().ops]
    n_rs = types.count("c_reducescatter")
    n_ag = types.count("c_allgather")
    assert plan.buckets and n_rs == n_ag == plan.n_buckets, (
        f"shard smoke FAILED: expected {plan.n_buckets} "
        f"reduce-scatter/allgather pairs, got {n_rs}/{n_ag}")
    n_adam_after = types.count("adam")
    assert n_adam_after == plan.n_buckets < n_adam_before, (
        f"shard smoke FAILED: per-param adam ops not coalesced "
        f"({n_adam_before} -> {n_adam_after}, {plan.n_buckets} buckets)")

    # -- shard shapes -------------------------------------------------------
    block = main.global_block()
    for b in plan.buckets:
        assert b["padded_len"] % WORLD == 0 and \
            b["shard_len"] * WORLD == b["padded_len"], b
        for name in b["slots"].values():
            v = block.var(name)
            assert v.persistable and v.attrs.get("dp_shard") == WORLD \
                and tuple(v.shape) == (b["padded_len"],), (name, v.shape)
            sv = startup.global_block().var(name)
            assert tuple(sv.shape) == (b["padded_len"],), name

    # -- estimator slot reduction ------------------------------------------
    one_bucket = max(b["padded_len"] for b in plan.buckets) * 4
    assert sharded["optimizer_slot_bytes"] <= \
        plain["optimizer_slot_bytes"] // WORLD + one_bucket, (
        f"shard smoke FAILED: sharded slot bytes "
        f"{sharded['optimizer_slot_bytes']} not <= plain/{WORLD} "
        f"({plain['optimizer_slot_bytes'] // WORLD}) + bucket")

    # only the compile-free rewrite+estimate phase is wall-asserted —
    # the mesh XLA compile below is host-load dependent (the tier-1
    # budget note in ROADMAP), so it is reported, never asserted
    rewrite_wall = time.time() - t0
    assert rewrite_wall < 15.0, (
        f"shard smoke FAILED: rewrite+estimate took {rewrite_wall:.1f}s "
        f"(>15s) — the sharding pass is no longer build-time cheap")

    # -- compile-once on the mesh ------------------------------------------
    compiled = CompiledProgram(main).with_data_parallel(loss_name=loss.name)
    exe = static.Executor()
    scope = static.Scope()
    rng = np.random.RandomState(0)

    def feed():
        return {"x": rng.rand(batch, 16).astype(np.float32),
                "y": rng.rand(batch, 1).astype(np.float32)}

    with static.scope_guard(scope):
        exe.run(startup)
        exe.run(compiled, feed=feed(), fetch_list=[loss])
        warm_compiles = len(compiled._cache)
        for _ in range(steps):
            out = exe.run(compiled, feed=feed(), fetch_list=[loss])
        assert np.isfinite(np.asarray(out[0])).all()
        # rank-sharded slot: the global array is [padded], each device
        # holds padded/WORLD elements
        sname = next(iter(plan.buckets[0]["slots"].values()))
        slot = scope.get(sname)
        shards = getattr(slot, "addressable_shards", None)
        if shards:
            per_rank = {tuple(s.data.shape) for s in shards}
            assert per_rank == {(plan.buckets[0]["shard_len"],)}, per_rank
    new_compiles = len(compiled._cache) - warm_compiles
    assert new_compiles == 0, (
        f"shard smoke FAILED: {new_compiles} recompile(s) after warmup "
        f"on the sharded program")

    # -- ZeRO-3 leg: full parameter sharding --------------------------------
    t3 = time.time()
    _reset_unique_names()
    main3, startup3 = static.Program(), static.Program()
    with static.program_guard(main3, startup3):
        x = layers.data("x", [-1, 16])
        y = layers.data("y", [-1, 1])
        h = layers.fc(x, 32, act="relu")
        pred = layers.fc(h, 1)
        loss3 = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.Adam(learning_rate=1e-2).minimize(loss3)
    plain3 = static.analyze_program(main3, batch=batch)
    plan3 = shard_optimizer_states(main3, startup3, dp_degree=WORLD,
                                   stage=3)
    sharded3 = static.analyze_program(main3, batch=batch)
    assert plan3.stage == 3 and plan3.param_bucket_names(), plan3
    blk3 = main3.global_block()
    # per-chip param bytes ≈ total/8: every param is packed into a
    # dp_shard bucket the walker charges 1/world (+ pow2 padding)
    pbytes = sum(blk3.var(n).shape[0] * 4
                 for n in plan3.param_bucket_names())
    raw_pbytes = sum(b["raw_len"] * 4 for b in plan3.buckets
                     if b.get("param_bucket"))
    assert sharded3["parameter_bytes"] <= \
        raw_pbytes // WORLD + len(plan3.buckets) * WORLD * 4, (
        f"shard smoke FAILED: zero3 per-chip param bytes "
        f"{sharded3['parameter_bytes']} not ~1/{WORLD} of {raw_pbytes}")
    assert sharded3["persistable_bytes"] < plain3["persistable_bytes"] // 4
    # JIT allgather present in FORWARD, no stage-1 publish
    from paddle_tpu.core.program import OpRole as _OpRole
    roles = [op.attrs.get("zero_role") for op in blk3.ops
             if op.type == "c_allgather"]
    assert roles.count("gather_fwd") == len(plan3.buckets) and \
        "publish" not in roles, roles
    first_mul = next(i for i, op in enumerate(blk3.ops)
                     if op.type == "mul")
    first_gather = next(i for i, op in enumerate(blk3.ops)
                        if op.attrs.get("zero_role") == "gather_fwd")
    assert first_gather < first_mul
    rewrite3_wall = time.time() - t3
    assert rewrite3_wall < 15.0, (
        f"shard smoke FAILED: zero3 rewrite+estimate took "
        f"{rewrite3_wall:.1f}s (>15s)")

    compiled3 = CompiledProgram(main3).with_data_parallel(
        loss_name=loss3.name)
    exe3 = static.Executor()
    scope3 = static.Scope()
    with static.scope_guard(scope3):
        exe3.run(startup3)
        exe3.run(compiled3, feed=feed(), fetch_list=[loss3])
        warm3 = len(compiled3._cache)
        for _ in range(steps):
            out3 = exe3.run(compiled3, feed=feed(), fetch_list=[loss3])
        assert np.isfinite(np.asarray(out3[0])).all()
        pb = scope3.get(plan3.param_bucket_names()[0])
        shards3 = getattr(pb, "addressable_shards", None)
        if shards3:
            b0 = next(b for b in plan3.buckets if b.get("param_bucket"))
            per_rank = {tuple(s.data.shape) for s in shards3}
            assert per_rank == {(b0["shard_len"],)}, per_rank
    new3 = len(compiled3._cache) - warm3
    assert new3 == 0, (
        f"shard smoke FAILED: {new3} recompile(s) after warmup on the "
        f"zero3 program")

    return {
        "metric": "shard_smoke_slot_reduction_x",
        "value": round(plain["optimizer_slot_bytes"]
                       / max(1, sharded["optimizer_slot_bytes"]), 2),
        "rewrite_wall_s": round(rewrite_wall, 2),
        "wall_s": round(time.time() - t0, 2),
        "buckets": plan.n_buckets,
        "plain_slot_bytes": plain["optimizer_slot_bytes"],
        "sharded_slot_bytes": sharded["optimizer_slot_bytes"],
        "compiles_after_warmup": new_compiles,
        "zero3_param_reduction_x": round(
            plain3["parameter_bytes"]
            / max(1, sharded3["parameter_bytes"]), 2),
        "zero3_buckets": plan3.n_buckets,
        "zero3_compiles_after_warmup": new3,
        "zero3_rewrite_wall_s": round(rewrite3_wall, 2),
    }


def main():
    steps = 4
    if "--steps" in sys.argv:
        steps = int(sys.argv[sys.argv.index("--steps") + 1])
    print(json.dumps(run_smoke(steps=steps)))


if __name__ == "__main__":
    main()

"""Flash-attention tuning sweep (run on real TPU hardware).

Measures the Pallas flash kernel vs XLA's fused attention across sequence
lengths and flash block sizes, prints a table plus the measured crossover,
and suggests the `flash_min_seq_len` / `flash_block_q` / `flash_block_k`
flag settings to pin.

    python tools/tune_flash.py                 # default sweep
    SEQS=512,1024,2048,4096 BLOCKS=128x256,256x512 python tools/tune_flash.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def bench_fn(fn, *args, iters=20):
    import jax
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.time()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.time() - t0) / iters


def main():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.attention import (flash_attention,
                                          reference_attention)

    dev = jax.devices()[0]
    print(f"device: {dev.platform} ({dev.device_kind})", flush=True)
    b, h, d = 4, 12, 64
    seqs = [int(s) for s in os.environ.get(
        "SEQS", "512,1024,2048,4096,8192").split(",")]
    blocks = [tuple(int(x) for x in bl.split("x")) for bl in os.environ.get(
        "BLOCKS", "128x128,128x256,256x256,256x512,512x512").split(",")]

    crossover = None
    for seq in seqs:
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.rand(b, h, seq, d), jnp.bfloat16)
        k = jnp.asarray(rng.rand(b, h, seq, d), jnp.bfloat16)
        v = jnp.asarray(rng.rand(b, h, seq, d), jnp.bfloat16)
        try:
            t_ref = bench_fn(jax.jit(
                lambda q, k, v: reference_attention(q, k, v, causal=True)),
                q, k, v)
        except Exception as e:  # O(S^2) OOM at long seq — flash territory
            print(f"seq {seq}: XLA reference failed ({type(e).__name__})")
            t_ref = float("inf")
        best = (float("inf"), None)
        for bq, bk in blocks:
            if bq > seq or bk > seq:
                continue
            try:
                t = bench_fn(jax.jit(
                    lambda q, k, v, bq=bq, bk=bk: flash_attention(
                        q, k, v, causal=True, block_q=bq, block_k=bk)),
                    q, k, v)
            except Exception as e:
                print(f"  seq {seq} block {bq}x{bk}: "
                      f"{type(e).__name__}: {e}")
                continue
            if t < best[0]:
                best = (t, (bq, bk))
        tok_ref = b * seq / t_ref if t_ref != float("inf") else 0
        tok_fl = b * seq / best[0] if best[1] else 0
        win = "FLASH" if best[0] < t_ref else "xla"
        print(f"seq {seq:6d}: xla {t_ref*1e3:8.2f}ms ({tok_ref:9.0f} "
              f"tok/s) | flash {best[0]*1e3:8.2f}ms ({tok_fl:9.0f} "
              f"tok/s) block {best[1]} -> {win}", flush=True)
        if crossover is None and best[0] < t_ref:
            crossover = (seq, best[1])

    if crossover:
        seq, (bq, bk) = crossover
        print(f"\ncrossover: flash wins from seq {seq}; suggest flags:")
        print(f"  FLAGS_flash_min_seq_len={seq}")
        print(f"  FLAGS_flash_block_q={bq} FLAGS_flash_block_k={bk}")
    else:
        print("\nflash never won in this sweep — keep the XLA path "
              "(raise flash_min_seq_len above the largest measured seq)")


if __name__ == "__main__":
    main()

"""Fast CPU 2-D-planner gate: the planner must pick a 4×2 dp×tp plan
UNPROMPTED — no ``variants=`` hand-feed of the winner — for a shape
where pure dp is walker-infeasible, and the applied plan must train on
the 8-device CPU mesh with zero post-warmup retraces.

The cheap canary for the 2-D planner tier (tests/test_tp_plan_smoke.py
runs it as a tier-1 test, mirroring plan_smoke/mem_smoke):

  1. build a toy transformer LM (plain, tp=1) and plan it once with the
     tp axis DISABLED to learn the best pure-dp walked peak under the
     same knob set;
  2. set the HBM budget strictly BETWEEN the best tp candidate's peak
     and the best pure-dp peak (derived at runtime from the trace, so
     the gate tracks the walker instead of baking in byte counts);
  3. re-plan with ``model_config=`` only — the tp variants are
     auto-generated through the tensor_parallel builders, never
     hand-fed — and require the chosen plan to be dp×tp = 4×2 with
     every pure-dp candidate walker-infeasible;
  4. apply the plan to the winning build variant, require
     ``check_program(level="all")`` strict-clean (the V6xx layout level
     included), and train it on the real 4×2 CPU mesh: finite
     decreasing loss, ZERO post-warmup retraces;
  5. the whole walk stays under the 15 s budget.

Prints one JSON line; correctness never depends on throughput.

Usage: python tools/tp_plan_smoke.py
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# the toy shape: activations dominate (batch×seq×4h intermediates), so
# tensor parallelism cuts what remat+ZeRO alone cannot
GEOM = dict(vocab_size=128, hidden=64, num_layers=2, num_heads=4,
            seq_len=32, learning_rate=1e-2)
WORLD, BATCH = 8, 16
# axes held fixed for determinism and speed: the gate is about the tp
# axis, and the budget below is derived under this same knob set
KNOBS = {"batch": (BATCH,), "grad_merge": (1,), "zero_stage": (1,)}


def _build_base():
    import paddle_tpu.static as static
    from paddle_tpu.core.program import _reset_unique_names
    from paddle_tpu.models import build_transformer_lm
    _reset_unique_names()
    main, startup, loss, _ = build_transformer_lm(
        vocab_size=GEOM["vocab_size"], hidden=GEOM["hidden"],
        num_layers=GEOM["num_layers"], num_heads=GEOM["num_heads"],
        seq_len=GEOM["seq_len"])
    with static.program_guard(main, startup):
        static.Adam(learning_rate=GEOM["learning_rate"]).minimize(loss)
    return main, startup, loss


def run_smoke():
    """Run the gate; returns the result dict (AssertionError on any
    2-D-planner regression)."""
    os.environ.setdefault("PADDLE_TPU_VERIFY", "warn")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu.static as static

    t0 = time.time()

    # -- 1. learn the pure-dp frontier under a loose budget ----------------
    main, startup, _ = _build_base()
    probe = static.plan_program(
        main, startup, world=WORLD, hbm_budget=1 << 50,
        knobs=dict(KNOBS, tp_degree=(0, 2)), model_config=GEOM,
        verify=False)
    dp_peaks = [c["peak_bytes"] for c in probe.trace
                if not c["tp_degree"] and c["peak_bytes"] > 0]
    tp_peaks = [c["peak_bytes"] for c in probe.trace
                if c["tp_degree"] == 2 and c["peak_bytes"] > 0]
    assert dp_peaks and tp_peaks, "probe trace missing candidates"
    best_dp, best_tp = min(dp_peaks), min(tp_peaks)
    assert best_tp < best_dp, (
        f"tp plan smoke FAILED: the tp=2 build no longer walks below "
        f"the best pure-dp candidate ({best_tp} >= {best_dp}) — the "
        f"tp HBM division regressed")
    # the fits verdict grants the calibrated XLA-remat slack, so the
    # budget sits just under best_dp/slack: every pure-dp candidate
    # misses even WITH the slack, while the tp walk (strictly below
    # best_dp) still clears it
    from paddle_tpu.static.memory_analysis import XLA_REMAT_SLACK
    budget = int(best_dp / XLA_REMAT_SLACK) - 1

    # -- 2/3. the real search: tp variants auto-generated, tight budget ----
    main, startup, _ = _build_base()
    plan = static.plan_program(
        main, startup, world=WORLD, hbm_budget=budget,
        knobs=dict(KNOBS), model_config=GEOM)
    assert plan.predicted_fits, (
        f"tp plan smoke FAILED: nothing fits at the derived budget "
        f"({budget} B)\n{plan.render_table()}")
    assert plan.knobs["tp_degree"] == 2, (
        f"tp plan smoke FAILED: planner chose "
        f"{plan.knobs} instead of the 4×2 dp×tp plan\n"
        f"{plan.render_table()}")
    for c in plan.trace:
        if not c["tp_degree"]:
            assert not c["fits"], (
                f"tp plan smoke FAILED: pure-dp candidate fits at the "
                f"tight budget — the gate lost its premise: {c}")
    chosen = [c for c in plan.trace if "chosen" in c["verdict"]]
    assert chosen and chosen[0]["verdict"].startswith("verified"), chosen
    # the per-axis wire split must price the mp ring at its OWN degree
    per_axis = plan.predicted_wire_bytes_per_axis
    assert per_axis.get("mp", 0) > 0, per_axis

    # -- 4. apply + train the winner on the real 4×2 mesh ------------------
    from paddle_tpu.distributed.compiled_program import (CompiledProgram,
                                                         BuildStrategy)
    win_main, win_startup, loss_name = plan.build_variants[2]
    static.apply_plan(win_main, win_startup, plan)
    report = static.check_program(win_main, level="all",
                                  startup=win_startup)
    assert report.ok, (
        "tp plan smoke FAILED: applied 2-D plan not strict-clean:\n"
        + report.render())
    assert "V504" not in report.codes()

    bs = BuildStrategy()
    bs.tensor_parallel_degree = 2
    compiled = CompiledProgram(win_main).with_data_parallel(
        loss_name=loss_name, build_strategy=bs)
    assert dict(compiled._get_mesh().shape) == {"dp": 4, "tp": 2}

    exe = static.Executor()
    scope = static.Scope()
    rng = np.random.RandomState(0)
    seq = GEOM["seq_len"]
    feed = {
        "ids": rng.randint(0, GEOM["vocab_size"],
                           (BATCH, seq)).astype(np.int64),
        "pos": np.tile(np.arange(seq), (BATCH, 1)).astype(np.int64),
        "labels": rng.randint(0, GEOM["vocab_size"],
                              (BATCH, seq, 1)).astype(np.int64),
    }
    losses = []
    with static.scope_guard(scope):
        exe.run(win_startup)
        for i in range(6):
            out = exe.run(compiled, feed=feed, fetch_list=[loss_name])
            losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
            if i == 0:
                warm = len(compiled._cache)
        assert len(compiled._cache) == warm, (
            "tp plan smoke FAILED: recompile after warmup "
            f"({len(compiled._cache)} != {warm})")
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses

    wall = time.time() - t0
    assert wall < 15.0, (
        f"tp plan smoke FAILED: {wall:.1f}s (>15s) — the 2-D search is "
        f"no longer estimator-cheap")
    return {
        "metric": "tp_plan_smoke_wall_s",
        "value": round(wall, 2),
        "unit": "s",
        "chosen_knobs": dict(plan.knobs),
        "budget_bytes": int(budget),
        "best_dp_peak_bytes": int(best_dp),
        "best_tp_peak_bytes": int(best_tp),
        "wire_bytes_per_axis": dict(per_axis),
        "losses": [round(v, 4) for v in losses],
        "n_candidates": len(plan.trace),
    }


if __name__ == "__main__":
    print(json.dumps(run_smoke()))

"""Fast CPU tp-serving gate: tp=2 page budget beats tp=1, sharded
decode is token-equal, zero post-warmup retraces.

The cheap canary for the tp-sharded decode tier
(tests/test_tp_serve_smoke.py runs it as a tier-1 test, mirroring
page_smoke/serve_smoke): sizes the SAME model's page pool with
``static.page_budget`` at tp=1 and tp=2 under one pinned per-chip HBM
budget, then asserts the contracts multi-chip serving rests on:

  * the tp=2 plan carves MORE pages than tp=1 at equal per-chip HBM —
    halving the per-chip weight + KV charge is the whole point of
    sharding the decode;
  * ``serving.TPShardedDecoder`` (the CompiledProgram the engine runs
    across the dp×mp mesh) produces the single-chip model's argmax
    token and its gathered KV columns bit-for-bit shape-equal on both
    a prefill bucket and a cached decode bucket;
  * repeating a warmed bucket adds ZERO jit traces — the decode
    program must ride its (batch, cache, width) bucket cache, never a
    fresh trace.

Prints one JSON line; correctness never depends on throughput.

Usage: python tools/tp_serve_smoke.py
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# pinned per-chip budget: weights + a thin KV grant so the tp=1 pool is
# starved and the tp=2 per-chip savings convert into visible pages
SMOKE_KV_GRANT = 256 * 1024


def run_smoke():
    """Run the gate; returns the result dict (AssertionError on any
    tp-serving contract regression)."""
    os.environ.setdefault("PADDLE_TPU_VERIFY", "warn")
    _flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu
    import paddle_tpu.dygraph as dg
    from paddle_tpu.core import compile_cache
    from paddle_tpu.models import GPTConfig, GPTModel
    from paddle_tpu.nn import MultiHeadAttention
    from paddle_tpu.serving import TPShardedDecoder
    from paddle_tpu.static import page_budget

    t0 = time.time()
    rng = np.random.RandomState(3)
    with dg.guard():
        cfg = GPTConfig(vocab_size=48, hidden_size=16, num_layers=2,
                        num_heads=4, max_position=64, dropout=0.0)
        m = GPTModel(cfg)
        m.eval()

        # -- planner budgets: tp=2 must out-carve tp=1 per chip --------
        weight_bytes = int(sum(np.asarray(p.numpy()).nbytes
                               for p in m.parameters()))
        hbm = weight_bytes + SMOKE_KV_GRANT
        plan1 = page_budget(m, page_tokens=4, max_context=64,
                            hbm_bytes=hbm)
        plan2 = page_budget(m, page_tokens=4, max_context=64,
                            hbm_bytes=hbm, tp_degree=2)
        assert plan2["pages"] > plan1["pages"], \
            f"tp=2 carved no extra pages: {plan2['pages']} vs " \
            f"{plan1['pages']} at equal per-chip HBM"

        # -- sharded decode token-equal on prefill + decode buckets ----
        dec = TPShardedDecoder(m, tp_degree=2)
        ids = rng.randint(0, 48, (1, 8)).astype(np.int64)
        zero = np.zeros(1, np.int64)
        lr, cr = m.forward(paddle_tpu.to_tensor(ids), cache=m.gen_cache(1),
                           pos_offset=zero, attn_mask=m._mask(8))
        lt, ct = dec.forward(paddle_tpu.to_tensor(ids),
                             cache=m.gen_cache(1), pos_offset=zero,
                             attn_mask=m._mask(8))
        a, b = np.asarray(lr.numpy()), np.asarray(lt.numpy())
        assert (a.argmax(-1) == b.argmax(-1)).all(), \
            "sharded prefill diverged from single-chip argmax"
        np.testing.assert_allclose(a, b, atol=1e-4)
        for li in range(cfg.num_layers):
            np.testing.assert_allclose(
                np.asarray(cr[li].k.numpy()), np.asarray(ct[li].k.numpy()),
                atol=1e-4, err_msg="gathered K columns diverged")

        S, lc = 2, 8
        H, Dh = cfg.num_heads, cfg.hidden_size // cfg.num_heads
        kv = (rng.randn(cfg.num_layers, 2, S, H, lc, Dh) * 0.1
              ).astype(np.float32)

        def cache():
            return [MultiHeadAttention.Cache(
                paddle_tpu.to_tensor(kv[li, 0].copy()),
                paddle_tpu.to_tensor(kv[li, 1].copy()))
                for li in range(cfg.num_layers)]

        ids2 = rng.randint(0, 48, (S, 1)).astype(np.int64)
        pos2 = np.full((S,), lc, np.int64)
        mask = np.zeros((S, 1, 1, lc + 1), np.float32)
        lr, _ = m.forward(paddle_tpu.to_tensor(ids2), cache=cache(),
                          pos_offset=pos2,
                          attn_mask=paddle_tpu.to_tensor(mask))
        lt, _ = dec.forward(paddle_tpu.to_tensor(ids2), cache=cache(),
                            pos_offset=pos2,
                            attn_mask=paddle_tpu.to_tensor(mask))
        a, b = np.asarray(lr.numpy()), np.asarray(lt.numpy())
        assert (a.argmax(-1) == b.argmax(-1)).all(), \
            "sharded decode diverged from single-chip argmax"

        # -- warmed buckets must not retrace ---------------------------
        s0 = compile_cache.cache_stats()
        dec.forward(paddle_tpu.to_tensor(ids2), cache=cache(),
                    pos_offset=pos2,
                    attn_mask=paddle_tpu.to_tensor(mask))
        dec.forward(paddle_tpu.to_tensor(ids), cache=m.gen_cache(1),
                    pos_offset=zero, attn_mask=m._mask(8))
        s1 = compile_cache.cache_stats()
        retraces = s1["traces"] - s0["traces"]
        assert retraces == 0, \
            f"warmed decode buckets retraced {retraces} time(s)"

    return {
        "metric": "tp_serve_smoke_wall_s",
        "value": round(time.time() - t0, 2),
        "pages_tp1": plan1["pages"],
        "pages_tp2": plan2["pages"],
        "page_capacity_ratio": round(plan2["pages"] /
                                     max(1, plan1["pages"]), 2),
        "buckets_compiled": dec.buckets_compiled,
        "traces_after_warmup": retraces,
        "token_equal": True,
    }


if __name__ == "__main__":
    print(json.dumps(run_smoke()))

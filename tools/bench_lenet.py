"""MNIST LeNet-5 training bench (BASELINE.md config 1 — the reference's
CPU-grade config; on TPU it is dispatch-bound, which run_steps absorbs).

LeNet-5 through the static API: conv-pool x2, fc x3, softmax CE, SGD.
Prints one bench.py-style JSON line (images/s)."""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_lenet(use_amp=False):
    import paddle_tpu.static as static
    from paddle_tpu.static import layers

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        im = layers.data("image", [-1, 1, 28, 28])
        lbl = layers.data("label", [-1, 1], dtype="int64")
        h = layers.conv2d(im, 6, 5, padding=2, act="relu")
        h = layers.pool2d(h, 2, pool_type="max", pool_stride=2)
        h = layers.conv2d(h, 16, 5, act="relu")
        h = layers.pool2d(h, 2, pool_type="max", pool_stride=2)
        h = layers.fc(h, 120, act="relu")
        h = layers.fc(h, 84, act="relu")
        logits = layers.fc(h, 10)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, lbl))
        static.SGD(learning_rate=0.01).minimize(loss)
    return main, startup, loss


def main():
    import jax
    if os.environ.get("BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.static as static

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    batch = int(os.environ.get("BENCH_BATCH", 256))
    k = int(os.environ.get("BENCH_MEGASTEP", 50 if on_tpu else 5))

    main_p, startup_p, loss = build_lenet()
    exe, scope = static.Executor(), static.Scope()
    rng = np.random.RandomState(0)
    sfeed = {
        "image": rng.rand(k, batch, 1, 28, 28).astype(np.float32),
        "label": rng.randint(0, 10, (k, batch, 1)).astype(np.int64),
    }
    with static.scope_guard(scope):
        exe.run(startup_p)
        exe.run_steps(main_p, feed=sfeed, fetch_list=[loss])  # compile
        t0 = time.time()
        out = exe.run_steps(main_p, feed=sfeed, fetch_list=[loss])
        np.asarray(out[0])
        dt = time.time() - t0

    print(json.dumps({
        "metric": "lenet_mnist_images_per_sec_per_chip" if on_tpu
                  else "lenet_mnist_cpu_images_per_sec",
        "value": round(k * batch / dt, 2),
        "unit": "images/s/chip",
        "vs_baseline": 0.0,
    }))


if __name__ == "__main__":
    main()

"""Fast CPU layout-analysis gate: a clean col→row tensor-parallel
program infers correct SPMD layouts with zero diagnostics, a seeded
missing-reduction defect is caught, in seconds.

The cheap canary for the sharding-propagation tier
(tests/test_layout_smoke.py runs it as a tier-1 test, mirroring
verify_smoke/shard_smoke): builds a Megatron col→row fc pair on a 4×2
``dp × mp`` mesh and asserts the contract the layout gate rests on:

  * the CLEAN program infers the full layout — column weight
    ``P(None, 'mp')``, row weight ``P('mp')``, the hidden activation
    feature-sharded, the row output replicated again — with ZERO V6xx
    diagnostics, and its reshard table prices the mp-ring allreduce at
    exact ring accounting (2(g−1)/g × bytes);
  * a seeded V602 (the row-parallel ``mp_allreduce_sum`` dropped — the
    partial products read as if complete) is caught with op provenance;
  * the whole walk (two full propagations + a level-"layout"
    check_program) stays under the 10 s budget.

Prints one JSON line; correctness never depends on throughput.

Usage: python tools/layout_smoke.py
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MESH = {"dp": 4, "mp": 2}
BATCH = 16


def build_tp_program(tp_degree: int = 2):
    """A minimized Megatron col→row training program (main, startup,
    loss)."""
    import paddle_tpu.static as static
    from paddle_tpu.static import layers
    from paddle_tpu.core.program import _reset_unique_names
    from paddle_tpu.distributed.tensor_parallel import (col_parallel_fc,
                                                        row_parallel_fc)

    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        h = col_parallel_fc(x, 16, act="relu", tp_degree=tp_degree)
        pred = row_parallel_fc(h, 16, tp_degree=tp_degree)
        loss = layers.mean(layers.square_error_cost(pred, y))
        static.SGD(learning_rate=0.05).minimize(loss)
    return main, startup, loss


def run_smoke():
    """Run the gate; returns the result dict (AssertionError on any
    layout-analyzer regression)."""
    # every tier-1 smoke doubles as a verifier sweep — "all" now
    # includes the layout level, so arming warn here sweeps V6xx too
    os.environ.setdefault("PADDLE_TPU_VERIFY", "warn")
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.static as static

    t0 = time.time()

    # -- clean program: full inference, zero diagnostics --------------------
    main, startup, loss = build_tp_program()
    layout = static.propagate_shardings(main, mesh_shape=MESH, batch=BATCH)
    assert not layout.diagnostics, (
        f"layout smoke FAILED: clean col→row program reported "
        f"{len(layout.diagnostics)} diagnostic(s): {layout.codes()}")
    col_w = layout.spec("col_parallel_fc_0.w_0")
    row_w = layout.spec("row_parallel_fc_0.w_0")
    hidden = layout.spec("col_parallel_fc_0.tmp_2")  # post-bias activation
    assert col_w.spec == (None, "mp"), col_w.render()
    assert row_w.spec == ("mp",), row_w.render()
    assert "mp" in hidden.axes(), hidden.render()
    # the row output (post-allreduce) must be replicated again
    part = next(n for n, s in layout.specs.items() if s.partial)
    assert part == "row_parallel_fc_0.tmp_0", part

    # reshard table: ONE mp conversion, priced at exact ring accounting
    mp_rows = [r for r in layout.reshard_table if r["axis"] == "mp"]
    assert len(mp_rows) == 1, layout.reshard_table
    g = MESH["mp"]
    expected = int(2 * (g - 1) / g * (BATCH * 16 * 4))  # [B,16] f32
    assert mp_rows[0]["bytes"] == expected, (mp_rows, expected)
    assert layout.wire_bytes_per_axis().get("mp") == expected

    # the verifier's layout level sees the same cleanliness
    report = static.check_program(main, level="layout", startup=startup,
                                  fetch_list=[loss])
    v6 = [d for d in report.diagnostics if d.code.startswith("V6")]
    assert not v6, report.render()

    # -- seeded defect: drop the row-parallel allreduce → V602 --------------
    dead_main, _, dead_loss = build_tp_program()
    dropped = 0
    for op in dead_main.global_block().ops:
        if op.type == "mp_allreduce_sum":
            op.type = "assign"
            op.attrs.pop("ring_id", None)
            dropped += 1
    dead_main._fingerprint_cache = None
    assert dropped == 1, dropped
    dead = static.propagate_shardings(dead_main, mesh_shape=MESH)
    v602 = [d for d in dead.diagnostics if d.code == "V602"]
    assert v602, (
        f"layout smoke FAILED: dropped mp_allreduce_sum not detected as "
        f"V602; got {dead.codes()}")
    assert v602[0].var == "row_parallel_fc_0.tmp_0", v602[0]
    assert v602[0].op_uid is not None

    wall = time.time() - t0
    assert wall < 10.0, (
        f"layout smoke FAILED: gate took {wall:.1f}s (>10s) — "
        f"compile-time analysis is no longer compile-time cheap")

    return {
        "metric": "layout_smoke_wall_s",
        "value": round(wall, 2),
        "clean_diagnostics": len(layout.diagnostics),
        "mp_reshard_bytes": mp_rows[0]["bytes"],
        "seeded_codes": dead.codes(),
        "iterations": layout.iterations,
    }


def main():
    print(json.dumps(run_smoke()))


if __name__ == "__main__":
    main()

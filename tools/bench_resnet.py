"""ResNet-50 ImageNet training bench (BASELINE.md config 2: images/sec/
chip, MFU tracked).  Builds ResNet-50 with the static-graph API
(bottleneck v1.5: stride-2 on the 3x3, like the reference's
vision/models/resnet.py lineage), runs momentum-SGD steps under bf16 AMP
as one scanned device dispatch (Executor.run_steps), and prints one JSON
line in the bench.py format.

MFU accounting: ~4.1 GFLOPs/image forward at 224^2 (standard count for
ResNet-50 v1.5), x3 for fwd+bwd.
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def conv_bn(layers, x, filters, ksize, stride=1, act=None):
    y = layers.conv2d(x, filters, ksize, stride=stride,
                      padding=(ksize - 1) // 2, bias_attr=False)
    return layers.batch_norm(y, act=act)


def bottleneck(layers, x, filters, stride, downsample):
    out = conv_bn(layers, x, filters, 1, act="relu")
    out = conv_bn(layers, out, filters, 3, stride=stride, act="relu")
    out = conv_bn(layers, out, filters * 4, 1)
    if downsample:
        x = conv_bn(layers, x, filters * 4, 1, stride=stride)
    return layers.relu(layers.elementwise_add(out, x))


def build_resnet50(batch, img=224, classes=1000, use_amp=True):
    import paddle_tpu.static as static
    from paddle_tpu.static import layers
    from paddle_tpu import amp

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        im = layers.data("image", [-1, 3, img, img])
        label = layers.data("label", [-1, 1], dtype="int64")
        h = conv_bn(layers, im, 64, 7, stride=2, act="relu")
        h = layers.pool2d(h, 3, pool_type="max", pool_stride=2,
                          pool_padding=1)
        for stage, (filters, blocks) in enumerate(
                [(64, 3), (128, 4), (256, 6), (512, 3)]):
            for b in range(blocks):
                stride = 2 if (b == 0 and stage > 0) else 1
                h = bottleneck(layers, h, filters, stride, b == 0)
        h = layers.pool2d(h, pool_type="avg", global_pooling=True)
        logits = layers.fc(h, classes)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        opt = static.Momentum(learning_rate=0.1, momentum=0.9)
        if use_amp:
            opt = amp.decorate(opt, init_loss_scaling=1.0,
                               use_dynamic_loss_scaling=False,
                               dest_dtype="bfloat16")
        opt.minimize(loss)
    return main, startup, loss


def main():
    import jax
    if os.environ.get("BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.static as static

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    batch = int(os.environ.get("BENCH_BATCH", 128 if on_tpu else 4))
    img = int(os.environ.get("BENCH_IMG", 224 if on_tpu else 32))
    classes = 1000 if on_tpu else 16
    k = int(os.environ.get("BENCH_MEGASTEP", 10 if on_tpu else 2))

    main_p, startup_p, loss = build_resnet50(batch, img, classes)
    exe, scope = static.Executor(), static.Scope()
    rng = np.random.RandomState(0)
    sfeed = {
        "image": rng.rand(k, batch, 3, img, img).astype(np.float32),
        "label": rng.randint(0, classes, (k, batch, 1)).astype(np.int64),
    }
    with static.scope_guard(scope):
        exe.run(startup_p)
        exe.run_steps(main_p, feed=sfeed, fetch_list=[loss])  # compile
        t0 = time.time()
        out = exe.run_steps(main_p, feed=sfeed, fetch_list=[loss])
        np.asarray(out[0])
        dt = time.time() - t0

    images_per_sec = k * batch / dt
    flops_per_image = 3 * 4.1e9 * (img / 224.0) ** 2
    peak = 197e12 if on_tpu else 0
    mfu = images_per_sec * flops_per_image / peak if peak else 0.0
    print(json.dumps({
        "metric": "resnet50_imagenet_images_per_sec_per_chip"
                  if on_tpu else "resnet50_tiny_cpu_images_per_sec",
        "value": round(images_per_sec, 2),
        "unit": "images/s/chip",
        "mfu": round(mfu, 4),
        "vs_baseline": round(mfu / 0.35, 4) if peak else 0.0,
    }))


if __name__ == "__main__":
    main()

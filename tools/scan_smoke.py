"""Fast CPU scanned-window gate: K->1 dispatches, ONE publish per
window, bitwise parity with the looped path, zero post-warmup retraces.

The cheap canary for the scanned micro-step hot path
(tests/test_scan_smoke.py runs it as a tier-1 test, mirroring
shard_smoke/mem_smoke): builds a small Adam model under ZeRO-2 x
gradient merge K on the 8-device CPU mesh and asserts the contracts the
tier rests on:

  * the window SPLITS — `split_commit_tail` finds a hoistable commit
    tail; the tail holds exactly one publish allgather per ZeRO bucket
    and the scan body holds none (the wire the hoist deletes);
  * dispatch collapse — K looped `Executor.run` calls become ONE
    `Executor.run_steps` device dispatch per window, and the compiled
    cache entry is the HOISTED variant (cache key carries the flag);
  * numerics are BITWISE — per-micro-step losses and every persistable
    (params, bucketed master state, gm counter) match the looped path
    bit for bit after the same feeds;
  * the host-side step counter and RNG phase stay aligned — a scanned
    window advances `_dispatches` by 1 but the training-step counter by
    K, so a following looped step lands on the same seed either way;
  * compile-once — after the first window, further windows never
    re-trace.

Prints one JSON line; correctness never depends on throughput.

Usage: python tools/scan_smoke.py [--windows 2]
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

WORLD = 8
GM_K = 4


def _build(static, layers, k):
    from paddle_tpu.core.program import _reset_unique_names
    from paddle_tpu.distributed.sharding import shard_optimizer_states
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 16])
        y = layers.data("y", [-1, 1])
        h = layers.fc(x, 32, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.Adam(learning_rate=1e-2).minimize(loss)
    plan = shard_optimizer_states(main, startup, dp_degree=WORLD, stage=2)
    static.gradient_merge(main, k, startup_program=startup)
    return main, startup, loss, plan


def run_smoke(windows: int = 2, batch: int = 8):
    """Run the gate; returns the result dict (AssertionError on a
    hoist, parity, or retrace regression)."""
    os.environ.setdefault("PADDLE_TPU_VERIFY", "warn")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={WORLD}"
        ).strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.static as static
    from paddle_tpu.static import layers, collective_sequence
    from paddle_tpu.distributed.compiled_program import CompiledProgram
    from paddle_tpu.distributed.scan_window import split_commit_tail

    t0 = time.time()
    k = GM_K
    main_l, startup_l, loss_l, _ = _build(static, layers, k)
    main_s, startup_s, loss_s, zplan = _build(static, layers, k)

    # -- the window splits, and the publish wire lives ONLY in the tail --
    split = split_commit_tail(main_s)
    assert split is not None and split.k == k, split
    tail_pub = [e for e in collective_sequence(split.tail)
                if e.get("zero_role") == "publish"]
    body_pub = [e for e in collective_sequence(split.body)
                if e.get("zero_role") == "publish"]
    assert len(tail_pub) == zplan.n_buckets and not body_pub, (
        f"scan smoke FAILED: publish allgathers tail={len(tail_pub)} "
        f"body={len(body_pub)}, want {zplan.n_buckets}/0 — the hoist "
        f"would not delete the masked re-publishes")
    rewrite_wall = time.time() - t0
    assert rewrite_wall < 15.0, (
        f"scan smoke FAILED: build+split took {rewrite_wall:.1f}s "
        f"(>15s) — the window split is no longer build-time cheap")

    # identical per-micro-step feeds for both paths
    rng = np.random.RandomState(0)
    feeds = [{"x": rng.rand(batch, 16).astype(np.float32),
              "y": rng.rand(batch, 1).astype(np.float32)}
             for _ in range(windows * k)]

    # -- looped path: K dispatches per window -------------------------------
    cp_l = CompiledProgram(main_l).with_data_parallel(loss_name=loss_l.name)
    exe_l = static.Executor()
    scope_l = static.Scope()
    losses_l = []
    with static.scope_guard(scope_l):
        exe_l.run(startup_l)
        d0 = cp_l._dispatches
        for f in feeds:
            out = exe_l.run(cp_l, feed=f, fetch_list=[loss_l])
            losses_l.append(np.asarray(out[0]))
        looped_disp = cp_l._dispatches - d0
    assert looped_disp == windows * k, (looped_disp, windows * k)

    # -- scanned path: ONE hoisted dispatch per window ----------------------
    cp_s = CompiledProgram(main_s).with_data_parallel(loss_name=loss_s.name)
    exe_s = static.Executor()
    scope_s = static.Scope()
    losses_s = []
    with static.scope_guard(scope_s):
        exe_s.run(startup_s)
        d0 = cp_s._dispatches
        warm = None
        for w in range(windows):
            sfeed = {n: np.stack([feeds[w * k + i][n] for i in range(k)])
                     for n in ("x", "y")}
            outs = exe_s.run_steps(cp_s, feed=sfeed, fetch_list=[loss_s])
            losses_s.extend(np.asarray(outs[0]))
            if warm is None:
                warm = len(cp_s._cache)
        scanned_disp = cp_s._dispatches - d0
        retraces = len(cp_s._cache) - warm
    assert scanned_disp == windows, (scanned_disp, windows)
    assert retraces == 0, (
        f"scan smoke FAILED: {retraces} recompile(s) after the first "
        f"window on the scanned program")
    hoisted_keys = [key for key in cp_s._cache
                    if key[0] == "steps" and key[1]]
    assert hoisted_keys, (
        "scan smoke FAILED: no HOISTED cache entry — run_steps fell "
        "back to the unhoisted scan (gate: splittable window, K %% "
        "gm_k == 0, PADDLE_TPU_SCAN_HOIST unset)")

    # -- bitwise parity -----------------------------------------------------
    assert len(losses_l) == len(losses_s) == windows * k
    for i, (a, b) in enumerate(zip(losses_l, losses_s)):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes(), (
            f"scan smoke FAILED: micro-step {i} loss differs "
            f"(looped {np.asarray(a)!r} vs scanned {np.asarray(b)!r})")
    blk = main_l.global_block()
    n_state = 0
    for name, v in blk.vars.items():
        if not v.persistable:
            continue
        a, b = scope_l.get(name), scope_s.get(name)
        if a is None or b is None:
            continue
        a, b = np.asarray(a), np.asarray(b)
        assert a.tobytes() == b.tobytes(), (
            f"scan smoke FAILED: persistable {name!r} differs after "
            f"{windows * k} steps (max abs diff "
            f"{np.max(np.abs(a.astype(np.float64) - b.astype(np.float64)))})")
        n_state += 1
    assert n_state >= 4, f"only {n_state} persistables compared"

    # -- host counter / RNG phase stay window-aligned -----------------------
    seed_l = exe_l._seed_for_step(main_l)
    seed_s = exe_s._seed_for_step(main_s)
    assert seed_l == seed_s, (
        f"scan smoke FAILED: RNG phase diverged — a looped step after "
        f"{windows * k} steps would seed {seed_l}, a post-window step "
        f"{seed_s}")

    return {
        "metric": "scan_smoke_dispatch_reduction_x",
        "value": round(looped_disp / max(1, scanned_disp), 2),
        "k": k,
        "windows": windows,
        "looped_dispatches": int(looped_disp),
        "scanned_dispatches": int(scanned_disp),
        "publish_allgathers_per_window": len(tail_pub),
        "persistables_bitwise_equal": n_state,
        "compiles_after_warmup": int(retraces),
        "rewrite_wall_s": round(rewrite_wall, 2),
        "wall_s": round(time.time() - t0, 2),
    }


def main():
    windows = 2
    if "--windows" in sys.argv:
        windows = int(sys.argv[sys.argv.index("--windows") + 1])
    print(json.dumps(run_smoke(windows=windows)))


if __name__ == "__main__":
    main()

"""Transformer-big WMT14 en-de training bench (BASELINE.md config 3).

Encoder-decoder built with the static-graph API (6+6 layers, d=1024,
16 heads, ffn 4096 — "Attention Is All You Need" big), label-smoothed
cross-entropy, Adam, bf16 AMP, one scanned device dispatch per K steps
(Executor.run_steps).  Attention masks ride as feed inputs exactly like
the reference's transformer book model feeds *_attn_bias tensors.

MFU accounting: 6 * params * processed tokens (src tokens through the
encoder params, trg tokens through the decoder params) + the score/
context matmul flops both stacks add; printed as one bench.py-style
JSON line.
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _mha(layers, q_in, kv_in, d_model, heads, bias=None):
    """Multi-head attention via raw static layers; bias is an additive
    [-1, 1, Tq, Tk] feed (None = unmasked)."""
    dk = d_model // heads

    def split_heads(x, t):
        y = layers.reshape(x, [-1, t, heads, dk])
        y.shape = (-1, t, heads, dk)
        return layers.transpose(y, [0, 2, 1, 3])

    tq, tk = q_in.shape[1], kv_in.shape[1]
    q = split_heads(layers.fc(q_in, d_model, num_flatten_dims=2), tq)
    k = split_heads(layers.fc(kv_in, d_model, num_flatten_dims=2), tk)
    v = split_heads(layers.fc(kv_in, d_model, num_flatten_dims=2), tk)
    logits = layers.matmul(layers.scale(q, scale=dk ** -0.5), k,
                           transpose_y=True)
    if bias is not None:
        logits = layers.elementwise_add(logits, bias)
    ctx = layers.matmul(layers.softmax(logits), v)
    ctx = layers.transpose(ctx, [0, 2, 1, 3])
    ctx = layers.reshape(ctx, [-1, tq, d_model])
    ctx.shape = (-1, tq, d_model)
    return layers.fc(ctx, d_model, num_flatten_dims=2)


def _block_post(layers, x, sub):
    return layers.layer_norm(layers.elementwise_add(x, sub),
                             begin_norm_axis=2)


def _ffn(layers, x, d_model, d_inner):
    h = layers.fc(x, d_inner, num_flatten_dims=2, act="relu")
    return layers.fc(h, d_model, num_flatten_dims=2)


def build_transformer_big(src_len, trg_len, vocab=32000, d_model=1024,
                          heads=16, n_layers=6, d_inner=4096,
                          use_amp=True):
    import paddle_tpu.static as static
    from paddle_tpu.static import layers
    from paddle_tpu import amp

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        src = layers.data("src_ids", [-1, src_len], dtype="int64")
        trg = layers.data("trg_ids", [-1, trg_len], dtype="int64")
        lbl = layers.data("labels", [-1, trg_len, 1], dtype="int64")
        causal = layers.data("trg_bias", [-1, 1, trg_len, trg_len])
        spos = layers.data("src_pos", [-1, src_len], dtype="int64")
        tpos = layers.data("trg_pos", [-1, trg_len], dtype="int64")

        enc = layers.elementwise_add(
            layers.embedding(src, size=[vocab, d_model]),
            layers.embedding(spos, size=[src_len, d_model]))
        for _ in range(n_layers):
            enc = _block_post(layers, enc,
                              _mha(layers, enc, enc, d_model, heads))
            enc = _block_post(layers, enc, _ffn(layers, enc, d_model,
                                                d_inner))

        dec = layers.elementwise_add(
            layers.embedding(trg, size=[vocab, d_model]),
            layers.embedding(tpos, size=[trg_len, d_model]))
        for _ in range(n_layers):
            dec = _block_post(layers, dec,
                              _mha(layers, dec, dec, d_model, heads,
                                   bias=causal))
            dec = _block_post(layers, dec,
                              _mha(layers, dec, enc, d_model, heads))
            dec = _block_post(layers, dec, _ffn(layers, dec, d_model,
                                                d_inner))

        logits = layers.fc(dec, vocab, num_flatten_dims=2)
        smoothed = layers.label_smooth(
            layers.one_hot(layers.reshape(lbl, [-1, trg_len]), vocab),
            epsilon=0.1)
        loss = layers.mean(layers.softmax_with_cross_entropy(
            logits, smoothed, soft_label=True))
        opt = static.Adam(learning_rate=2e-4)
        if use_amp:
            opt = amp.decorate(opt, init_loss_scaling=1.0,
                               use_dynamic_loss_scaling=False,
                               dest_dtype="bfloat16")
        opt.minimize(loss)
    return main, startup, loss


def main():
    import jax
    if os.environ.get("BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.static as static

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    if on_tpu:
        src_len = trg_len = int(os.environ.get("BENCH_SEQ", 256))
        batch = int(os.environ.get("BENCH_BATCH", 16))
        vocab, d_model, heads, n_layers, d_inner = (32000, 1024, 16, 6,
                                                    4096)
        k = int(os.environ.get("BENCH_MEGASTEP", 10))
    else:
        src_len = trg_len = 32
        batch, vocab, d_model, heads, n_layers, d_inner = 2, 512, 128, 4, 2, 256
        k = 2

    main_p, startup_p, loss = build_transformer_big(
        src_len, trg_len, vocab, d_model, heads, n_layers, d_inner)
    exe, scope = static.Executor(), static.Scope()
    rng = np.random.RandomState(0)
    causal = np.triu(np.full((trg_len, trg_len), -1e9, np.float32), 1)
    sfeed = {
        "src_ids": rng.randint(0, vocab, (k, batch, src_len), np.int64),
        "trg_ids": rng.randint(0, vocab, (k, batch, trg_len), np.int64),
        "labels": rng.randint(0, vocab, (k, batch, trg_len, 1), np.int64),
        "trg_bias": np.broadcast_to(
            causal, (k, batch, 1, trg_len, trg_len)).copy(),
        "src_pos": np.broadcast_to(np.arange(src_len, dtype=np.int64),
                                   (k, batch, src_len)).copy(),
        "trg_pos": np.broadcast_to(np.arange(trg_len, dtype=np.int64),
                                   (k, batch, trg_len)).copy(),
    }
    with static.scope_guard(scope):
        exe.run(startup_p)
        exe.run_steps(main_p, feed=sfeed, fetch_list=[loss])  # compile
        t0 = time.time()
        out = exe.run_steps(main_p, feed=sfeed, fetch_list=[loss])
        np.asarray(out[0])
        dt = time.time() - t0

    tokens = k * batch * (src_len + trg_len)
    tokens_per_sec = tokens / dt
    n_params = sum(int(np.prod(v.shape))
                   for v in main_p.all_parameters() if v.shape is not None)
    # params split ~40/60 enc/dec (dec adds cross-attn); use 6*P_total/2
    # per processed token as both stacks see half the tokens, plus
    # score/context matmuls: 12 * L * T * d per token per stack
    flops = (6 * n_params * tokens / 2
             + 12 * n_layers * src_len * d_model * tokens)
    peak = 197e12 if on_tpu else 0
    mfu = flops / dt / peak if peak else 0.0
    print(json.dumps({
        "metric": "transformer_big_wmt_tokens_per_sec_per_chip"
                  if on_tpu else "transformer_tiny_cpu_tokens_per_sec",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s/chip",
        "mfu": round(mfu, 4),
        "vs_baseline": round(mfu / 0.35, 4) if peak else 0.0,
    }))


if __name__ == "__main__":
    main()

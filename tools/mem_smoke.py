"""Fast CPU memory-accounting gate: bert-tiny, estimator + remat, hard
assertions.

The cheap canary for the memory-for-throughput tier
(tests/test_mem_smoke.py runs it as a tier-1 test, mirroring
perf_smoke/ckpt_smoke): builds bert-tiny twice — plain and with
FLAGS_recompute=always auto-selected layer checkpoints — and asserts
the contract the HBM accounting rests on:

  * the estimator walks BOTH programs in seconds (<10 s for the whole
    estimate phase — compile-time accounting must stay compile-time
    cheap);
  * remat's walked activation peak shows the expected reduction vs the
    plain program (the rewrite actually cuts live ranges, not just adds
    barrier ops);
  * the rewritten program still honors the compile-once contract: a
    short training run traces at most the two steady signatures and
    NEVER re-traces after warmup (remat must not poison the step cache).

Prints one JSON line; correctness never depends on throughput.

Usage: python tools/mem_smoke.py [--steps 4]
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_smoke(steps: int = 4, batch: int = 8):
    """Run the gate; returns the result dict (AssertionError on an
    estimator or retrace regression)."""
    # every tier-1 smoke doubles as a verifier sweep (ISSUE 10):
    # armed here, the first-compile hook and the rewrite-pass
    # self-checks verify every program this gate builds, for free
    os.environ.setdefault("PADDLE_TPU_VERIFY", "warn")
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.static as static
    from paddle_tpu.core.flags import set_flags
    from paddle_tpu.core.program import _reset_unique_names
    import perf_smoke

    # -- estimate phase: must stay compile-time cheap -----------------------
    t_est = time.time()
    _reset_unique_names()
    main_plain, _, _, _ = perf_smoke.build_bert_tiny()
    _reset_unique_names()
    set_flags({"recompute": "always"})
    try:
        main_remat, startup_remat, loss_remat, _ = \
            perf_smoke.build_bert_tiny()
    finally:
        set_flags({"recompute": ""})
    plain = static.analyze_program(main_plain, batch=batch)
    remat = static.analyze_program(main_remat, batch=batch)
    est_wall = time.time() - t_est

    assert est_wall < 10.0, (
        f"mem smoke FAILED: estimate phase took {est_wall:.1f}s (>10s) — "
        f"compile-time accounting is no longer compile-time cheap")
    n_barriers = sum(1 for op in main_remat.global_block().ops
                     if op.type == "optimization_barrier")
    assert n_barriers >= 1, \
        "mem smoke FAILED: FLAGS_recompute=always inserted no barriers"
    assert remat["activation_peak_bytes"] < plain["activation_peak_bytes"], (
        f"mem smoke FAILED: remat activation peak "
        f"{remat['activation_peak_bytes']} not below plain "
        f"{plain['activation_peak_bytes']}")
    assert remat["persistable_bytes"] == plain["persistable_bytes"], \
        "mem smoke FAILED: remat changed the persistable footprint"

    # -- retrace gate: the rewritten program keeps compile-once -------------
    exe = static.Executor()
    scope = static.Scope()
    rng = np.random.RandomState(0)
    idt = np.int64 if jax.config.jax_enable_x64 else np.int32
    vocab = 512

    def make_batch(b):
        return {"ids": rng.randint(0, vocab, (b, 32)).astype(idt),
                "labels": rng.randint(0, vocab, (b, 32, 1)).astype(idt)}

    with static.scope_guard(scope):
        exe.run(startup_remat)
        warm = make_batch(batch)
        exe.run(main_remat, feed=warm, fetch_list=[loss_remat])
        exe.run(main_remat, feed=warm, fetch_list=[])
        warm_traces = exe.cache_stats()["traces"]
        for _ in range(steps):
            exe.run(main_remat, feed=warm, fetch_list=[])
        # ragged tail must bucket into the compiled executable
        exe.run(main_remat, feed=make_batch(max(1, batch - 1)),
                fetch_list=[])
        out = exe.run(main_remat, feed=warm, fetch_list=[loss_remat])
        assert np.isfinite(np.asarray(out[0])).all()
    stats = exe.cache_stats()
    new_traces = stats["traces"] - warm_traces
    assert new_traces == 0, (
        f"mem smoke FAILED: {new_traces} recompile(s) after warmup on the "
        f"remat program (stats {stats})")

    return {
        "metric": "mem_smoke_remat_peak_reduction_pct",
        "value": round((1.0 - remat["activation_peak_bytes"]
                        / plain["activation_peak_bytes"]) * 100, 1),
        "estimate_wall_s": round(est_wall, 2),
        "plain_peak_bytes": plain["peak_bytes"],
        "remat_peak_bytes": remat["peak_bytes"],
        "barriers": n_barriers,
        "traces": stats["traces"],
        "traces_after_warmup": new_traces,
    }


def main():
    steps = 4
    if "--steps" in sys.argv:
        steps = int(sys.argv[sys.argv.index("--steps") + 1])
    print(json.dumps(run_smoke(steps=steps)))


if __name__ == "__main__":
    main()

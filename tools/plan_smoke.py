"""Fast CPU auto-parallel-planner gate: plan a toy transformer, prove
the plan is strict-clean and ties-or-beats the no-knob baseline, and
exercise the `bench.py --auto` dry-run path — in seconds.

The cheap canary for the planner tier (tests/test_plan_smoke.py runs it
as a tier-1 test, mirroring mem_smoke/verify_smoke):

  * `static.plan_program` on a bert-tiny training program returns a
    plan whose knob point exists in the trace, was VERIFIED
    (`check_program(level="collective")` clean), and whose predicted
    step time ties or beats the knob-free baseline candidate — the
    argmax property the whole tier rests on;
  * applying the plan (`static.apply_plan`) leaves a program that
    passes `check_program(level="collective")` under strict mode with
    ZERO diagnostics, including the V504 plan-drift check against the
    recorded registry entry;
  * `bench.py --auto --dry-run` (the plan+apply path `bench.py --auto`
    runs before measuring) emits a well-formed plan JSON;
  * the whole walk stays under the 10 s budget — compile-time search
    must stay compile-time cheap.

Prints one JSON line; correctness never depends on throughput.

Usage: python tools/plan_smoke.py
"""
from __future__ import annotations

import contextlib
import io
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def run_smoke():
    """Run the gate; returns the result dict (AssertionError on any
    planner regression)."""
    # every tier-1 smoke doubles as a verifier sweep (ISSUE 10): armed
    # here, the executor/rewrite first-compile hooks verify for free
    os.environ.setdefault("PADDLE_TPU_VERIFY", "warn")
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.static as static
    from paddle_tpu.core.program import _reset_unique_names
    import perf_smoke

    t0 = time.time()

    # -- plan a toy transformer --------------------------------------------
    _reset_unique_names()
    main, startup, loss, _ = perf_smoke.build_bert_tiny()
    plan = static.plan_program(main, startup, world=8, batch=8,
                               knobs={"grad_merge": (1,)})
    assert plan.trace, "plan smoke FAILED: empty candidate trace"
    chosen_in_trace = [c for c in plan.trace if "chosen" in c["verdict"]]
    assert chosen_in_trace, \
        "plan smoke FAILED: chosen knobs not marked in the trace"
    assert plan.predicted_fits, (
        f"plan smoke FAILED: bert-tiny plan predicted over budget "
        f"({plan.predicted_peak_bytes} bytes)")

    # argmax property: the chosen plan ties or beats the knob-free
    # baseline candidate on predicted step time
    baseline = [c for c in plan.trace
                if not c["remat"] and c["dp_shard"] == 0
                and c["grad_merge"] == 1 and not c["ring"]]
    assert baseline, "plan smoke FAILED: no knob-free baseline in trace"
    assert plan.predicted_step_ms <= baseline[0]["step_ms"] + 1e-9, (
        f"plan smoke FAILED: chosen plan ({plan.predicted_step_ms:.4f} ms) "
        f"is WORSE than the no-knob baseline "
        f"({baseline[0]['step_ms']:.4f} ms)")

    # -- applied plan is strict-clean (incl. V504 drift check) -------------
    static.apply_plan(main, startup, plan)
    report = static.check_program(main, level="collective",
                                  startup=startup, fetch_list=[loss])
    assert not report.diagnostics, (
        f"plan smoke FAILED: applied plan not strict-clean:\n"
        f"{report.render()}")
    from paddle_tpu.core.pass_framework import has_applied
    assert has_applied(main, "auto_parallel_plan"), \
        "plan smoke FAILED: plan not recorded in the applied-passes registry"

    # -- bench --auto dry-run path -----------------------------------------
    import bench
    argv, env = list(sys.argv), dict(os.environ)
    buf = io.StringIO()
    try:
        sys.argv = ["bench.py", "--auto", "--dry-run"]
        os.environ.update({"BENCH_FORCE_CPU": "1", "BENCH_SEQ": "32",
                           "BENCH_LAYERS": "1", "BENCH_HIDDEN": "64",
                           "BENCH_HEADS": "2", "BENCH_VOCAB": "256",
                           "BENCH_BATCH": "4"})
        with contextlib.redirect_stdout(buf):
            bench.auto_main()
    finally:
        sys.argv = argv
        os.environ.clear()
        os.environ.update(env)
    auto = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert auto.get("dry_run") and auto["metric"] == \
        "auto_plan_tokens_per_sec", \
        f"plan smoke FAILED: malformed --auto dry-run record: {auto}"
    assert "auto_parallel_plan" in auto["applied_passes"], \
        "plan smoke FAILED: --auto did not record the plan"
    assert auto["plan"]["predicted_fits"] is True

    wall = time.time() - t0
    assert wall < 10.0, (
        f"plan smoke FAILED: {wall:.1f}s (>10s) — the planner is no "
        f"longer estimator-cheap")
    return {
        "metric": "plan_smoke_wall_s",
        "value": round(wall, 2),
        "unit": "s",
        "n_candidates": len(plan.trace),
        "chosen_knobs": dict(plan.knobs),
        "predicted_step_ms": round(plan.predicted_step_ms, 4),
        "baseline_step_ms": round(baseline[0]["step_ms"], 4),
        "auto_dry_run_ok": True,
    }


if __name__ == "__main__":
    print(json.dumps(run_smoke()))

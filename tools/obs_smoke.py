"""Fast CPU observability gate: exact FLOPs on a hand-countable toy,
one journaled train step, non-empty Prometheus exposition — in seconds.

The cheap canary for the telemetry tier (tests/test_obs_smoke.py runs it
as a tier-1 test, mirroring verify_smoke/mem_smoke):

  * `static.analyze_flops` on a 2-layer toy MLP matches the matmul
    FLOPs counted by hand from the layer shapes (fwd 2·M·K·N, bwd 2×) —
    the walker's arithmetic, not just its plumbing;
  * one training step with the run journal armed produces parseable
    JSONL whose `step` event carries the step/wall-time schema, and a
    heartbeat file with the same step;
  * `monitor.prometheus_text()` renders the train.* metrics that step
    minted (TYPE lines present, non-empty);
  * the whole gate stays under the 10 s budget.

Prints one JSON line; correctness never depends on throughput.

Usage: python tools/obs_smoke.py
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

IN, H1, H2 = 16, 32, 8
BATCH = 4


def build_toy():
    """2-layer MLP whose matmul FLOPs are countable on one hand."""
    import paddle_tpu.static as static
    from paddle_tpu.static import layers
    from paddle_tpu.core.program import _reset_unique_names
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, IN])
        y = layers.data("y", [-1, 1])
        h = layers.fc(x, H1, act="relu")
        h = layers.fc(h, H2, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.SGD(learning_rate=1e-2).minimize(loss)
    return main, startup, loss


def hand_counted_matmul_flops(batch: int) -> int:
    """fwd: 2·B·K·N per fc; bwd (dX + dW): 2× fwd."""
    fwd = 2 * batch * (IN * H1 + H1 * H2 + H2 * 1)
    return fwd * 3


def run_smoke():
    # every tier-1 smoke doubles as a verifier sweep (ISSUE 10):
    # armed here, the first-compile hook and the rewrite-pass
    # self-checks verify every program this gate builds, for free
    os.environ.setdefault("PADDLE_TPU_VERIFY", "warn")
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu.static as static
    from paddle_tpu.core import monitor
    from paddle_tpu import observability as obs

    t0 = time.time()

    # -- FLOPs walker vs hand count -----------------------------------------
    main, startup, loss = build_toy()
    rep = static.analyze_flops(main, batch=BATCH)
    want = hand_counted_matmul_flops(BATCH)
    got = rep["by_class"].get("matmul", 0)
    assert got == want, (
        f"obs smoke FAILED: walker matmul FLOPs {got} != hand-counted "
        f"{want} on the 2-layer toy")
    assert rep["phase_flops"]["forward"] > 0
    assert rep["phase_flops"]["backward"] > rep["phase_flops"]["forward"]

    # -- one journaled train step -------------------------------------------
    jdir = tempfile.mkdtemp(prefix="obs_smoke_journal_")
    obs.set_journal_dir(jdir)
    try:
        exe, scope = static.Executor(), static.Scope()
        rng = np.random.RandomState(0)
        feed = {"x": rng.rand(BATCH, IN).astype(np.float32),
                "y": rng.rand(BATCH, 1).astype(np.float32)}
        with static.scope_guard(scope):
            exe.run(startup)
            exe.run(main, feed=feed, fetch_list=[loss])
    finally:
        obs.set_journal_dir(None)
    journals = obs.read_rank_journals(jdir)
    assert 0 in journals and journals[0], (
        f"obs smoke FAILED: no parseable journal under {jdir}")
    kinds = [e["kind"] for e in journals[0]]
    assert "run_start" in kinds and "step" in kinds, kinds
    step_ev = next(e for e in journals[0] if e["kind"] == "step")
    for key in ("run_id", "rank", "seq", "t", "step", "wall_ms"):
        assert key in step_ev, (key, step_ev)
    seqs = [e["seq"] for e in journals[0]]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs), seqs

    # -- Prometheus exposition ----------------------------------------------
    text = monitor.prometheus_text()
    assert text.strip(), "obs smoke FAILED: empty prometheus_text()"
    assert "# TYPE train_steps_total counter" in text, text[:400]
    assert "train_step_ms" in text, text[:400]

    wall = time.time() - t0
    assert wall < 10.0, (
        f"obs smoke FAILED: gate took {wall:.1f}s (>10s)")
    return {
        "metric": "obs_smoke_wall_s",
        "value": round(wall, 2),
        "matmul_flops": got,
        "hand_counted_flops": want,
        "total_flops": rep["total_flops"],
        "journal_events": len(journals[0]),
        "journal_kinds": sorted(set(kinds)),
        "prometheus_bytes": len(text),
    }


def main():
    print(json.dumps(run_smoke()))


if __name__ == "__main__":
    main()

"""Fast CPU perf gate: bert-tiny, ~20 steps, hard recompile assertions.

The cheap canary for the executor hot path (tests/test_perf_smoke.py runs
it as a tier-1 test): builds a bert-tiny pretraining step, runs a short
epoch whose batches ride the async Prefetcher and whose FINAL BATCH IS
RAGGED, then asserts the compile-once contract:

  * at most ``max_traces`` whole-block traces total (fetch + no-fetch
    signatures), and — the regression that matters — ZERO new traces
    after warmup: the ragged tail batch must be served by shape
    bucketing, not a fresh jit;
  * the prefetched loop preserved batch order (checked through a
    per-row fetch of the step's token ids).

Prints one JSON line with steady-state tokens/s so perf runs can eyeball
the number; correctness of the gate never depends on throughput (CI
machines are noisy).

Usage: python tools/perf_smoke.py [--steps 20]
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_bert_tiny(vocab=512, seq=32, hidden=64, layers_n=2, heads=2):
    import paddle_tpu.static as static
    from paddle_tpu.static import layers, nets

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        ids = layers.data("ids", [-1, seq], dtype="int64")
        labels = layers.data("labels", [-1, seq, 1], dtype="int64")
        h = layers.embedding(ids, size=[vocab, hidden])
        h = layers.layer_norm(h, begin_norm_axis=2)
        for _ in range(layers_n):
            q = layers.fc(h, hidden, num_flatten_dims=2)
            k = layers.fc(h, hidden, num_flatten_dims=2)
            v = layers.fc(h, hidden, num_flatten_dims=2)
            ctx = nets.scaled_dot_product_attention(q, k, v, num_heads=heads)
            h = layers.layer_norm(layers.elementwise_add(h, ctx),
                                  begin_norm_axis=2)
            ffn = layers.fc(h, hidden * 2, num_flatten_dims=2, act="gelu")
            h = layers.layer_norm(
                layers.elementwise_add(h, layers.fc(ffn, hidden,
                                                    num_flatten_dims=2)),
                begin_norm_axis=2)
        logits = layers.fc(h, vocab, num_flatten_dims=2)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, labels))
        static.Adam(learning_rate=1e-4).minimize(loss)
    return main, startup, loss, ids


def run_smoke(steps=20, batch=4, seq=32, max_traces=2, cache_dir=None):
    """Run the gate; returns the result dict (raises AssertionError on a
    recompile regression)."""
    # every tier-1 smoke doubles as a verifier sweep (ISSUE 10):
    # armed here, the first-compile hook and the rewrite-pass
    # self-checks verify every program this gate builds, for free
    os.environ.setdefault("PADDLE_TPU_VERIFY", "warn")
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.static as static
    from paddle_tpu.core import compile_cache

    if cache_dir is not None:
        compile_cache.initialize(cache_dir, min_compile_time_s=0.0,
                                 force=True)
    else:
        compile_cache.initialize()

    vocab = 512
    main, startup, loss, _ = build_bert_tiny(vocab=vocab, seq=seq)
    exe = static.Executor()
    scope = static.Scope()
    rng = np.random.RandomState(0)
    idt = np.int64 if jax.config.jax_enable_x64 else np.int32

    def make_batch(b):
        return {"ids": rng.randint(0, vocab, (b, seq)).astype(idt),
                "labels": rng.randint(0, vocab, (b, seq, 1)).astype(idt)}

    with static.scope_guard(scope):
        exe.run(startup)
        # warmup: compile the two steady signatures (fetch / no-fetch)
        warm = make_batch(batch)
        exe.run(main, feed=warm, fetch_list=[loss])
        exe.run(main, feed=warm, fetch_list=[])
        warm_stats = exe.cache_stats()

        # epoch with a RAGGED FINAL BATCH — batch-1 tail must bucket-pad
        # into the compiled executable, not trace a new one
        feeds = [make_batch(batch) for _ in range(steps - 1)]
        feeds.append(make_batch(max(1, batch - 1)))
        t0 = time.time()
        n_tok = 0
        for i, _out in enumerate(exe.run_prefetched(main, feeds,
                                                    fetch_list=[],
                                                    return_numpy=False)):
            n_tok += feeds[i]["ids"].shape[0] * seq
        out = exe.run(main, feed=warm, fetch_list=[loss])
        float(np.asarray(out[0]))
        dt = time.time() - t0

    stats = exe.cache_stats()
    new_traces = stats["traces"] - warm_stats["traces"]
    assert new_traces == 0, (
        f"perf smoke FAILED: {new_traces} recompile(s) after warmup "
        f"(stats {stats})")
    assert stats["traces"] <= max_traces, (
        f"perf smoke FAILED: {stats['traces']} total traces > "
        f"{max_traces} (stats {stats})")
    assert stats["bucket_hits"] >= 1, (
        f"perf smoke FAILED: ragged tail batch never hit a bucket "
        f"(stats {stats})")
    result = {
        "metric": "perf_smoke_tokens_per_sec",
        "value": round(n_tok / dt, 2),
        "steps": steps,
        "traces": stats["traces"],
        "traces_after_warmup": new_traces,
        "bucket_hits": stats["bucket_hits"],
        "persistent_dir": stats["persistent_dir"],
    }
    return result


def main():
    steps = 20
    if "--steps" in sys.argv:
        steps = int(sys.argv[sys.argv.index("--steps") + 1])
    print(json.dumps(run_smoke(steps=steps)))


if __name__ == "__main__":
    main()

"""On-chip A/B for the Pallas fused softmax-cross-entropy kernel
(ops/fused_xent.py) vs XLA's log_softmax+gather at the bench shape
([batch*seq, 30522] logits) and a few block configs.

Run ON TPU:  python tools/tune_fused_xent.py
Prints a table; paste the winner into docs/perf.md and flip
FLAGS_fused_xent in bench.py / training configs if the kernel wins.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timed(fn, *args, n=20):
    fn(*args)[0].block_until_ready() if isinstance(fn(*args), tuple) else \
        jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n * 1e3


def main():
    from paddle_tpu.ops.fused_xent import fused_softmax_xent

    dev = jax.devices()[0]
    print(f"device: {dev.platform} {getattr(dev, 'device_kind', '')}")
    interpret = dev.platform != "tpu"
    if interpret:
        print("WARNING: not on TPU — interpreter timings are meaningless; "
              "run this on the chip")

    rng = np.random.RandomState(0)
    results = []
    for T, V, dtype in [(16384, 30522, jnp.bfloat16),
                        (8192, 30522, jnp.bfloat16),
                        (16384, 30522, jnp.float32)]:
        logits = jnp.asarray(rng.randn(T, V), dtype)
        labels = jnp.asarray(rng.randint(0, V, (T,)), jnp.int32)

        @jax.jit
        def xla_ce(lg):
            lp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
            return -lp[jnp.arange(T), labels]

        @jax.jit
        def xla_ce_grad(lg):
            return jax.grad(lambda l: jnp.sum(
                -jax.nn.log_softmax(l.astype(jnp.float32))[
                    jnp.arange(T), labels]))(lg)

        base_f = timed(xla_ce, logits)
        base_b = timed(xla_ce_grad, logits)
        row = {"T": T, "V": V, "dtype": str(jnp.dtype(dtype)),
               "xla_fwd_ms": round(base_f, 3),
               "xla_fwdbwd_ms": round(base_b, 3), "pallas": {}}
        for bt, bv in [(128, 2048), (256, 2048), (256, 4096),
                       (512, 2048)]:
            try:
                @jax.jit
                def pallas_ce(lg):
                    return fused_softmax_xent(lg, labels, -100, bt, bv,
                                              interpret)

                @jax.jit
                def pallas_grad(lg):
                    return jax.grad(lambda l: jnp.sum(
                        fused_softmax_xent(l, labels, -100, bt, bv,
                                           interpret)))(lg)

                f = timed(pallas_ce, logits)
                b = timed(pallas_grad, logits)
                row["pallas"][f"bt{bt}_bv{bv}"] = {
                    "fwd_ms": round(f, 3), "fwdbwd_ms": round(b, 3),
                    "fwd_speedup": round(base_f / f, 3),
                    "fwdbwd_speedup": round(base_b / b, 3)}
            except Exception as e:  # config rejected by Mosaic
                row["pallas"][f"bt{bt}_bv{bv}"] = {"error": str(e)[:120]}
        results.append(row)
        print(row)
    import json
    print(json.dumps(results))


if __name__ == "__main__":
    main()

"""Fast CPU static-analysis gate: clean program verifies clean, seeded
deadlock + read-after-donate are caught, in seconds.

The cheap canary for the IR-verifier tier (tests/test_verify_smoke.py
runs it as a tier-1 test, mirroring mem_smoke/shard_smoke): builds a
small ZeRO-1-sharded training program and asserts the contract the
static-analysis gate rests on:

  * a CLEAN program (minimize + shard_optimizer_states on the 8-way
    plan) produces ZERO diagnostics at every level — the verifier must
    not cry wolf on the machinery the rewrite passes actually emit;
  * a seeded DEADLOCK (a collective hoisted into a control-flow
    sub-block — rank-divergent trip counts hang a real mesh) is caught
    with code V205;
  * a seeded READ-AFTER-DONATE (a forward-role op reading a parameter
    after its optimizer commit — the donated-buffer ordering bug) is
    caught with code V302;
  * the whole walk (three full-program verifications, including the
    abstract-evaluation shape check) stays under the 10 s budget —
    compile-time analysis must stay compile-time cheap.

Prints one JSON line; correctness never depends on throughput.

Usage: python tools/verify_smoke.py
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_sharded_program(dp_degree: int = 8):
    """A small minimized + ZeRO-1-sharded training program (main,
    startup, loss)."""
    import paddle_tpu.static as static
    from paddle_tpu.static import layers
    from paddle_tpu.core.program import _reset_unique_names
    from paddle_tpu.distributed.sharding import shard_optimizer_states

    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 16])
        y = layers.data("y", [-1, 1])
        h = layers.fc(x, 32, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        opt = static.Adam(learning_rate=1e-3)
        opt.minimize(loss)
    shard_optimizer_states(main, startup, dp_degree=dp_degree)
    return main, startup, loss


def run_smoke():
    """Run the gate; returns the result dict (AssertionError on any
    verifier regression)."""
    # every tier-1 smoke doubles as a verifier sweep (ISSUE 10):
    # armed here, the first-compile hook and the rewrite-pass
    # self-checks verify every program this gate builds, for free
    os.environ.setdefault("PADDLE_TPU_VERIFY", "warn")
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.static as static
    from paddle_tpu.core.program import OpDesc, OpRole

    t0 = time.time()

    # -- clean program: zero diagnostics ------------------------------------
    main, startup, loss = build_sharded_program()
    clean = static.check_program(main, level="all", startup=startup,
                                 fetch_list=[loss])
    assert not clean.diagnostics, (
        f"verify smoke FAILED: clean sharded program reported "
        f"{len(clean.diagnostics)} diagnostic(s):\n{clean.render()}")
    n_collectives = len(static.collective_sequence(main))
    assert n_collectives >= 2, (
        f"verify smoke FAILED: collective_sequence saw {n_collectives} "
        f"ops in a ZeRO-1 program (expected the rs/ag chain)")

    # -- seeded deadlock: collective under control flow ---------------------
    dead_main, dead_startup, dead_loss = build_sharded_program()
    sub = dead_main.create_block()
    dead_main.rollback()
    sub.ops.append(OpDesc("c_allreduce_sum", {"X": ["x"]}, {"Out": ["x"]},
                          {"ring_id": 0,
                           "op_uid": dead_main._next_uid()}))
    dead_main._fingerprint_cache = None
    dead = static.check_program(dead_main, level="all",
                                fetch_list=[dead_loss])
    assert any(d.code == "V205" for d in dead.errors), (
        f"verify smoke FAILED: seeded rank-conditional collective "
        f"(deadlock) not detected as V205; got {dead.codes()}")

    # -- seeded read-after-donate -------------------------------------------
    rad_main, rad_startup, rad_loss = build_sharded_program()
    blk = rad_main.global_block()
    param = rad_main.all_parameters()[0]
    blk.create_var(name="post_commit_read", shape=param.shape,
                   dtype=param.dtype, stop_gradient=True)
    blk.ops.append(OpDesc(
        "scale", {"X": [param.name]}, {"Out": ["post_commit_read"]},
        {"scale": 2.0, OpRole.KEY: OpRole.Forward,
         "op_uid": rad_main._next_uid()}))
    rad_main._fingerprint_cache = None
    rad = static.check_program(rad_main, level="all",
                               fetch_list=[rad_loss])
    assert any(d.code == "V302" for d in rad.errors), (
        f"verify smoke FAILED: seeded read-after-donate not detected "
        f"as V302; got {rad.codes()}")

    wall = time.time() - t0
    assert wall < 10.0, (
        f"verify smoke FAILED: gate took {wall:.1f}s (>10s) — "
        f"compile-time analysis is no longer compile-time cheap")

    return {
        "metric": "verify_smoke_wall_s",
        "value": round(wall, 2),
        "clean_diagnostics": len(clean.diagnostics),
        "collectives_extracted": n_collectives,
        "deadlock_codes": dead.codes(),
        "read_after_donate_codes": rad.codes(),
    }


def main():
    print(json.dumps(run_smoke()))


if __name__ == "__main__":
    main()

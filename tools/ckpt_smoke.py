"""Checkpoint gate: corrupt checkpoints must never load; resume must work.

The fault-tolerance analog of tools/perf_smoke.py (tests/test_ckpt_smoke.py
runs it as a tier-1 test, <30 s on CPU): trains a tiny static model with
periodic async checkpointing, then attacks the checkpoint directory the
two ways a preemption/bad disk does and asserts the recovery contract:

  * TRUNCATION — the newest step's shard is cut short (the artifact a
    mid-write kill leaves if atomicity is violated out-of-band):
    ``latest_step()`` must skip it;
  * BIT-FLIP — the next step's shard is corrupted in place without
    changing its size: ``load()`` must refuse it on CRC and fall back,
    with a RuntimeWarning;
  * RESUME — a fresh Executor restores from the surviving step and
    training continues.

Prints one JSON line; correctness never depends on throughput.

Usage: python tools/ckpt_smoke.py [--steps 6]
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import time
import warnings

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_tiny():
    import paddle_tpu.static as static
    from paddle_tpu.static import layers
    from paddle_tpu.core.program import _reset_unique_names
    # deterministic names across "restarts" in one process
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        h = layers.fc(x, 16, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.Adam(learning_rate=1e-2).minimize(loss)
    return main, startup, loss


def _clip(path: str, keep_bytes: int):
    with open(path, "r+b") as f:
        f.truncate(keep_bytes)


def _flip(path: str, offset: int = 7):
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))


def run_smoke(steps: int = 6, root: str = None):
    """Run the gate; returns the result dict (AssertionError on a
    robustness regression)."""
    # every tier-1 smoke doubles as a verifier sweep (ISSUE 10):
    # armed here, the first-compile hook and the rewrite-pass
    # self-checks verify every program this gate builds, for free
    os.environ.setdefault("PADDLE_TPU_VERIFY", "warn")
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.static as static
    from paddle_tpu.checkpoint import CheckpointManager

    t_start = time.time()
    root = root or tempfile.mkdtemp(prefix="ckpt_smoke_")
    rng = np.random.RandomState(0)
    feeds = [{"x": rng.rand(4, 8).astype(np.float32),
              "y": rng.rand(4, 1).astype(np.float32)}
             for _ in range(steps)]

    main, startup, loss = build_tiny()
    exe = static.Executor()
    scope = static.Scope()
    mgr = CheckpointManager(root, keep_last_n=steps + 1)
    with static.scope_guard(scope):
        exe.run(startup)
        exe.enable_checkpointing(mgr, program=main, every_n_steps=1,
                                 scope=scope)
        for f in feeds:
            exe.run(main, feed=f, fetch_list=[loss])
    mgr.wait()
    saved = mgr.all_steps()
    assert len(saved) >= 3, (
        f"ckpt smoke FAILED: expected >=3 checkpoints, got {saved}")
    newest, second, survivor = saved[-1], saved[-2], saved[-3]

    # attack 1: truncate the newest shard → latest_step() must skip it
    shard = os.path.join(mgr.step_dir(newest), "shard_00000.bin")
    _clip(shard, os.path.getsize(shard) // 2)
    got = mgr.latest_step()
    assert got == second, (
        f"ckpt smoke FAILED: latest_step()={got} did not skip the "
        f"truncated step {newest}")

    # attack 2: bit-flip the second-newest shard → CRC refusal + fallback
    _flip(os.path.join(mgr.step_dir(second), "shard_00000.bin"))
    mgr.close()

    # "restart": fresh manager + executor + scope, auto-resume
    mgr2 = CheckpointManager(root)
    main2, startup2, loss2 = build_tiny()
    exe2 = static.Executor()
    scope2 = static.Scope()
    with static.scope_guard(scope2):
        exe2.run(startup2)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            resumed = exe2.restore_from_checkpoint(mgr2, program=main2,
                                                   scope=scope2)
        fallback_warned = any(isinstance(w.message, RuntimeWarning)
                              for w in caught)
        assert resumed == survivor, (
            f"ckpt smoke FAILED: resumed from {resumed}, expected the "
            f"last valid step {survivor} (truncated {newest}, "
            f"bit-flipped {second})")
        assert fallback_warned, (
            "ckpt smoke FAILED: corrupt-checkpoint fallback produced no "
            "RuntimeWarning")
        # training continues from the restored state
        (val,) = exe2.run(main2, feed=feeds[0], fetch_list=[loss2])
        assert np.isfinite(np.asarray(val)).all()
    mgr2.close()

    from paddle_tpu.core.monitor import stat_get
    result = {
        "metric": "ckpt_smoke_resume_step",
        "value": resumed,
        "saved_steps": saved,
        "truncated_step": newest,
        "bitflipped_step": second,
        "load_fallbacks": stat_get("checkpoint.load_fallbacks"),
        "wall_s": round(time.time() - t_start, 2),
    }
    return result


def main():
    steps = 6
    if "--steps" in sys.argv:
        steps = int(sys.argv[sys.argv.index("--steps") + 1])
    print(json.dumps(run_smoke(steps=steps)))


if __name__ == "__main__":
    main()

"""Serving gate: dynamic batching must coalesce and must not retrace.

The serving analog of tools/perf_smoke.py (tests/test_serve_smoke.py
runs it as a tier-1 test): saves a tiny fc model, starts the HTTP
inference server with dynamic batching, warms every pow2 feed bucket the
load can touch, then fires N concurrent clients and asserts the serving
contract:

  * ZERO jit retraces after warmup — coalesced batches of any size must
    ride the predictor's pow2 buckets, never a fresh trace;
  * ``serving.batch.coalesced`` > 0 — concurrent requests actually
    shared device batches (the whole point of the tier);
  * every client got byte-exact rows for ITS request back.

Prints one JSON line with steady-state QPS + latency percentiles;
correctness of the gate never depends on throughput (CI boxes are
noisy).

Usage: python tools/serve_smoke.py [--clients 6] [--requests 10]
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def save_tiny_model(model_dir: str, in_dim: int = 8, classes: int = 3,
                    hidden: int = 0, depth: int = 0):
    """Save an fc(+relu stack)+softmax inference model; returns
    (ref_input, ref_output) for row-exactness checks.  ``hidden``/
    ``depth`` grow the model so per-run device time dominates HTTP
    overhead (the serving bench's regime)."""
    import paddle_tpu.static as static
    from paddle_tpu.static import layers
    from paddle_tpu.io.framework_io import save_inference_model

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, in_dim])
        h = x
        for _ in range(depth):
            h = layers.fc(h, hidden, act="relu")
        out = layers.fc(h, classes, act="softmax")
    exe = static.Executor()
    scope = static.Scope()
    xb = np.random.RandomState(0).rand(4, in_dim).astype(np.float32)
    with static.scope_guard(scope):
        exe.run(startup)
        save_inference_model(model_dir, ["x"], [out], exe, main)
        (ref,) = exe.run(main, feed={"x": xb}, fetch_list=[out])
    return xb, np.asarray(ref), out.name


def http_json(url: str, payload=None, timeout: float = 60.0):
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def run_load(base_url: str, payloads, clients: int, requests: int,
             check=None):
    """Steady-state load driver: ``clients`` threads each POST ``requests``
    times to /predict over ONE keep-alive connection (payload
    round-robined per client); returns wall seconds.  ``check(reply,
    payload_idx)`` validates each reply."""
    import http.client
    from urllib.parse import urlsplit
    barrier = threading.Barrier(clients + 1)
    errors = []
    netloc = urlsplit(base_url).netloc

    def client(cid):
        conn = http.client.HTTPConnection(netloc, timeout=60)
        bodies = [json.dumps(p).encode() for p in payloads]
        barrier.wait()
        try:
            for i in range(requests):
                k = (cid + i) % len(payloads)
                try:
                    conn.request("POST", "/predict", bodies[k],
                                 {"Content-Type": "application/json"})
                    resp = conn.getresponse()
                    reply = json.loads(resp.read())
                    if resp.status != 200:
                        raise AssertionError(f"HTTP {resp.status}: {reply}")
                    if check is not None:
                        check(reply, k)
                except Exception as e:  # noqa: BLE001 — surfaced below
                    errors.append(f"client {cid} req {i}: {e}")
                    return
        finally:
            conn.close()

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(clients)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.time()
    for t in threads:
        t.join()
    dt = time.time() - t0
    if errors:
        raise AssertionError("serve smoke load errors:\n  " +
                             "\n  ".join(errors[:10]))
    return dt


def run_smoke(clients: int = 6, requests: int = 10, max_batch: int = 8,
              max_wait_ms: float = 10.0, model_dir: str = None):
    """Run the gate; returns the result dict (AssertionError on a
    coalescing or retrace regression)."""
    # every tier-1 smoke doubles as a verifier sweep (ISSUE 10):
    # armed here, the first-compile hook and the rewrite-pass
    # self-checks verify every program this gate builds, for free
    os.environ.setdefault("PADDLE_TPU_VERIFY", "warn")
    import jax
    jax.config.update("jax_platforms", "cpu")
    import tempfile
    from paddle_tpu.inference.server import InferenceServer
    from paddle_tpu.serving.metrics import reset_serving_stats

    model_dir = model_dir or tempfile.mkdtemp(prefix="serve_smoke_")
    xb, ref, out_name = save_tiny_model(model_dir)
    reset_serving_stats()
    srv = InferenceServer(model_dir, max_batch=max_batch,
                          max_wait_ms=max_wait_ms)
    srv.start()
    try:
        base = f"http://{srv.host}:{srv.port}"
        # warm every pow2 bucket a coalesced batch can land in: request
        # batch b pads to the next pow2, so {1,2,4,...,max_batch} covers
        # any coalesced size
        b = 1
        while b <= max_batch:
            http_json(base + "/predict",
                      {"inputs": {"x": np.repeat(xb[:1], b, 0).tolist()}})
            b <<= 1
        warm_traces = http_json(base + "/stats")[
            "predictor_cache"]["traces"]

        # steady state: each client fires batch-1 rows of xb (row j),
        # checking it gets row j of the reference back
        payloads = [{"inputs": {"x": xb[j:j + 1].tolist()}}
                    for j in range(xb.shape[0])]

        def check(reply, k):
            got = np.asarray(reply["outputs"][out_name]["data"]).reshape(
                reply["outputs"][out_name]["shape"])
            np.testing.assert_allclose(got, ref[k:k + 1],
                                       rtol=1e-4, atol=1e-6)

        dt = run_load(base, payloads, clients, requests, check)
        stats = http_json(base + "/stats")
    finally:
        srv.stop()

    s = stats["serving"]
    traces = stats["predictor_cache"]["traces"]
    coalesced = s.get("serving.batch.coalesced", 0)
    assert traces == warm_traces, (
        f"serve smoke FAILED: {traces - warm_traces} retrace(s) after "
        f"warmup (stats {stats['predictor_cache']})")
    assert coalesced > 0, (
        f"serve smoke FAILED: no request coalescing under {clients} "
        f"concurrent clients (serving stats {s})")
    lat = s.get("serving.latency_ms", {})
    n_req = clients * requests
    result = {
        "metric": "serve_smoke_steady_qps",
        "value": round(n_req / dt, 2),
        "clients": clients,
        "requests": n_req,
        "coalesced_batches": coalesced,
        "batch_runs": s.get("serving.batch.runs", 0),
        "traces_after_warmup": traces - warm_traces,
        "p50_ms": round(lat.get("p50", 0.0), 3),
        "p99_ms": round(lat.get("p99", 0.0), 3),
    }
    return result


def main():
    args = sys.argv[1:]

    def opt(name, default):
        return int(args[args.index(name) + 1]) if name in args else default

    print(json.dumps(run_smoke(clients=opt("--clients", 6),
                               requests=opt("--requests", 10))))


if __name__ == "__main__":
    main()

"""Fast CPU gate for the int8 serving path: int8 pages carve ~2x the
fp32 pool at a pinned budget, int8 decode stays token-equal to the
fp32 engine, radix hits and speculative accepts ride int8 pages, zero
post-warmup retraces, leak-free drain.

The cheap canary for the quantized serving tier
(tests/test_int8_serve_smoke.py runs it as a tier-1 test, mirroring
page_smoke/spec_smoke/tp_serve_smoke):

  * one pinned HBM budget (weights + a thin KV grant) sized by
    ``static.page_budget`` at fp32 and at kv_dtype/weight_dtype
    ="int8" — the int8 plan must carve >= 1.9x the pages (int8 KV
    halves page bytes net of the fp32 scale sidecar, int8 weights
    return ~3/4 of the decode-matmul weight bytes to the carve);
  * an int8 engine (``quantize_decode_model``'s Int8Linear sibling
    over int8 pages) with radix retention and a full-depth speculative
    draft reproduces the fp32 paged engine's greedy output token for
    token — the tested tolerance on this model is EQUALITY (see
    docs/serving.md for the acceptance rule);
  * the second identical prompt hits the radix tree (prefill runs only
    the uncovered suffix) and speculation commits > 1 token per verify
    step — both riding QUANTIZED pages;
  * the compiled KV buckets stop growing after warmup, the scale-clip
    counter stays zero, and the drained pool reports zero leaks.

Prints one JSON line; correctness never depends on throughput.

Usage: python tools/int8_serve_smoke.py
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# pinned budget: weights + a thin KV grant, so the fp32 pool is starved
# and the int8 savings convert into visible pages
SMOKE_KV_GRANT = 256 * 1024


def run_smoke():
    """Run the gate; returns the result dict (AssertionError on any
    int8-serving contract regression)."""
    os.environ.setdefault("PADDLE_TPU_VERIFY", "warn")
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.dygraph as dg
    from paddle_tpu.models import GPTConfig, GPTModel, GPTForGeneration
    from paddle_tpu.serving import (ContinuousBatchingEngine, PagedKVPool,
                                    RadixPrefixCache, SpeculativeDecoder,
                                    metrics, stamp_draft)
    from paddle_tpu.static import page_budget

    t0 = time.time()
    rng = np.random.RandomState(13)
    with dg.guard():
        # pin the process-wide init generator: the token-EQUALITY
        # contract below is per-model, so the weights must not drift
        # with whatever ran earlier in this process (tier-1 wrapper)
        import paddle_tpu
        paddle_tpu.seed(1234)
        cfg = GPTConfig(vocab_size=48, hidden_size=16, num_layers=2,
                        num_heads=2, max_position=64, dropout=0.0)
        m = GPTForGeneration(GPTModel(cfg))
        m.eval()

        # -- planner budgets: int8 must out-carve fp32 >= 1.9x ---------
        weight_bytes = int(sum(np.asarray(p.numpy()).nbytes
                               for p in m.gpt.parameters()))
        hbm = weight_bytes + SMOKE_KV_GRANT
        plan_f = page_budget(m, page_tokens=4, max_context=64,
                             hbm_bytes=hbm, draft_layers=2)
        plan_i = page_budget(m, page_tokens=4, max_context=64,
                             hbm_bytes=hbm, draft_layers=2,
                             kv_dtype="int8", weight_dtype="int8")
        ratio = plan_i["pages"] / max(1, plan_f["pages"])
        assert ratio >= 1.9, \
            f"int8 carved only {ratio:.2f}x fp32 pages " \
            f"({plan_i['pages']} vs {plan_f['pages']}) at equal HBM"

        pa = rng.randint(2, 48, (9,)).astype(np.int64)
        pb = rng.randint(2, 48, (9,)).astype(np.int64)
        # fp32 references through the plain paged engine (itself
        # token-equal to generate(), gated by page_smoke)
        ref_pool = PagedKVPool.from_plan(plan_f)
        ref_eng = ContinuousBatchingEngine(m, max_slots=2,
                                           kv_pool=ref_pool).start()
        try:
            refs = {key: np.asarray(
                        ref_eng.submit(p, max_length=6).result(timeout=60))
                    for key, p in (("a", pa), ("b", pb))}
        finally:
            ref_eng.stop()
        ref_pool.assert_drained()

        # -- the int8 engine: quantized weights + pages + radix + spec -
        pool = PagedKVPool.from_plan(plan_i)
        assert pool.is_quantized and pool.stats()["kv_dtype"] == "int8"
        radix = RadixPrefixCache.from_plan(pool)
        spec = SpeculativeDecoder(stamp_draft(m, num_layers=2), k=3)
        eng = ContinuousBatchingEngine(m, max_slots=2, kv_pool=pool,
                                       prefix_cache=radix,
                                       speculative=spec)
        assert eng.weight_dtype == "int8"
        eng.start()
        try:
            # -- warmup: cold prefill + radix-hit reuse shapes ---------
            out = eng.submit(pa, max_length=6).result(timeout=60)
            np.testing.assert_array_equal(
                out, refs["a"], err_msg="int8 decode diverged from fp32")
            out = eng.submit(pa, max_length=6).result(timeout=60)
            np.testing.assert_array_equal(out, refs["a"])
            warm_buckets = eng.kv_buckets

            # -- radix hit skips prefill over QUANTIZED pages ----------
            pre_prefill = metrics.counter("gen.prefill_tokens")
            pre_hit = metrics.counter("kv.radix_hit_tokens")
            pre_steps = metrics.counter("spec.steps")
            pre_tokens = metrics.counter("gen.tokens")
            out = eng.submit(pa, max_length=6).result(timeout=60)
            np.testing.assert_array_equal(out, refs["a"])
            prefill_ran = metrics.counter("gen.prefill_tokens") - pre_prefill
            hit_tokens = metrics.counter("kv.radix_hit_tokens") - pre_hit
            assert hit_tokens > 0, \
                "second identical prompt missed the radix tree"
            assert prefill_ran < pa.size, "radix hit skipped no compute"

            # -- speculation commits > 1 token per verify step ---------
            spec_steps = metrics.counter("spec.steps") - pre_steps
            committed = metrics.counter("gen.tokens") - pre_tokens
            accepted_per_step = committed / max(1, spec_steps)
            assert accepted_per_step > 1.0, \
                f"speculation bought nothing on int8 pages: " \
                f"{committed} tokens over {spec_steps} verify steps"

            # -- cold second prompt: no new compiled shapes ------------
            out = eng.submit(pb, max_length=6).result(timeout=60)
            np.testing.assert_array_equal(out, refs["b"])
            buckets_after = eng.kv_buckets
        finally:
            eng.stop()
        retraces = buckets_after - warm_buckets
        assert retraces == 0, \
            f"{retraces} new compiled KV buckets after warmup"
        stats = pool.stats()
        assert stats["quant_scale_clips"] == 0, \
            f"{stats['quant_scale_clips']} scale clips — the " \
            f"requantize-on-grow policy must never clip"
        retained = pool.pages_retained
        assert retained > 0, "retirement inserted nothing into the tree"
        pool.assert_drained()    # retained pages are clean, not leaks
        radix.clear()
        pool.assert_drained()

    wall = time.time() - t0
    return {
        "metric": "int8_serve_smoke_wall_s",
        "value": round(wall, 2),
        "unit": "s",
        "pages_fp32": plan_f["pages"],
        "pages_int8": plan_i["pages"],
        "page_capacity_ratio": round(ratio, 2),
        "kv_dtype": stats["kv_dtype"],
        "quant_scale_clips": stats["quant_scale_clips"],
        "radix_hit_tokens": int(hit_tokens),
        "prefill_tokens_on_hit": int(prefill_ran),
        "accepted_per_step": round(accepted_per_step, 2),
        "retained_pages_at_drain": int(retained),
        "traces_after_warmup": retraces,
        "token_equal": True,
    }


def main():
    print(json.dumps(run_smoke()))


if __name__ == "__main__":
    main()

"""INT8 vs bf16 matmul throughput A/B on the current device.

Validates the premise of the int8 inference path (slim/quantization.py +
quant_int8_pass + int8_matmul): the v5e MXU runs int8 dots at 2x the
bf16 rate (394 vs 197 TOPS peak).  Measures a [M,K]x[K,N] dot at
BERT-ffn-like shapes through the same preferred_element_type=int32
lowering the int8_matmul kernel uses, and prints one JSON line with the
achieved TOPS for each dtype and the speed ratio.
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_dot(dtype, M, K, N, iters=30):
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    if dtype == "int8":
        a = jnp.asarray(rng.randint(-127, 127, (M, K)), jnp.int8)
        b = jnp.asarray(rng.randint(-127, 127, (K, N)), jnp.int8)
        acc = jnp.int32
    else:
        a = jnp.asarray(rng.rand(M, K), jnp.bfloat16)
        b = jnp.asarray(rng.rand(K, N), jnp.bfloat16)
        acc = jnp.float32

    @jax.jit
    def many(a, b):
        # chain iters dependent dots so one dispatch covers the loop and
        # XLA cannot hoist any of them (result feeds a cheap elementwise
        # perturbation of a)
        def body(carry, _):
            a_ = carry
            out = jax.lax.dot(a_, b, preferred_element_type=acc)
            nxt = (a_ + out[:, :1].astype(a_.dtype)) if dtype != "int8" \
                else jnp.bitwise_xor(a_, out[:, :1].astype(jnp.int8))
            return nxt, out[0, 0]
        carry, outs = jax.lax.scan(body, a, None, length=iters)
        return outs

    many(a, b).block_until_ready()  # compile
    t0 = time.time()
    many(a, b).block_until_ready()
    dt = time.time() - t0
    return 2.0 * M * K * N * iters / dt


def main():
    import jax
    if os.environ.get("BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")
    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    M, K, N = (8192, 3072, 3072) if on_tpu else (256, 256, 256)
    bf16 = bench_dot("bf16", M, K, N)
    i8 = bench_dot("int8", M, K, N)
    print(json.dumps({
        "metric": "int8_vs_bf16_matmul_tops",
        "value": round(i8 / 1e12, 2),
        "unit": "TOPS(int8)",
        "bf16_tflops": round(bf16 / 1e12, 2),
        "int8_speedup": round(i8 / bf16, 3),
        "vs_baseline": 0.0,
    }))


if __name__ == "__main__":
    main()

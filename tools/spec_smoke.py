"""Fast CPU gate for the retained prefix cache + speculative decoding:
radix hits skip prefill compute, spec decode stays token-equal with
accepted-tokens/step > 1, zero post-warmup retraces, leak-free drain
with retention active.

The cheap canary for the compute-sharing serving tier
(tests/test_spec_smoke.py runs it as a tier-1 test, mirroring
page_smoke):

  * a planner-sized pool (``page_budget(draft_layers=2)`` — the draft's
    weights and dense KV are charged before pages are carved) with a
    ``RadixPrefixCache`` at the plan's ``retained_watermarks``;
  * the SECOND submission of an identical prompt hits the radix tree:
    its prefill runs attention over strictly fewer tokens than the
    prompt (``kv.radix_hit_tokens`` counts exactly the skipped ones)
    and the output stays token-equal to ``generate()``;
  * speculative decode through a ``stamp_draft`` sibling (full-depth
    copy of the 2-layer target, so proposals agree and acceptance is
    total) emits MORE than one token per target step, token-equal;
  * the compiled KV buckets stop growing after warmup (radix reuse and
    k-wide verify steps must not leak new shapes per request), and the
    drained pool reports zero leaks while still holding retained pages.

Prints one JSON line; correctness never depends on throughput.

Usage: python tools/spec_smoke.py
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# small enough that the pool slab + draft KV are a few hundred KB of
# host numpy, big enough for retention + the churn run
SMOKE_HBM_BYTES = 4 * 1024 * 1024


def run_smoke():
    """Run the gate; returns the result dict (AssertionError on any
    compute-sharing contract regression)."""
    os.environ.setdefault("PADDLE_TPU_VERIFY", "warn")
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.dygraph as dg
    from paddle_tpu.models import GPTConfig, GPTModel, GPTForGeneration
    from paddle_tpu.serving import (ContinuousBatchingEngine, PagedKVPool,
                                    RadixPrefixCache, SpeculativeDecoder,
                                    metrics, stamp_draft)
    from paddle_tpu.static import page_budget

    t0 = time.time()
    rng = np.random.RandomState(13)
    with dg.guard():
        cfg = GPTConfig(vocab_size=48, hidden_size=16, num_layers=2,
                        num_heads=2, max_position=64, dropout=0.0)
        m = GPTForGeneration(GPTModel(cfg))
        m.eval()

        plan = page_budget(m, page_tokens=4, hbm_bytes=SMOKE_HBM_BYTES,
                           draft_layers=2)
        assert plan["draft_kv_bytes"] > 0 and plan["draft_weight_bytes"] > 0
        wm = plan["retained_watermarks"]
        assert 0 < wm["low"] < wm["high"] <= plan["pages"], wm
        pool = PagedKVPool.from_plan(plan)
        radix = RadixPrefixCache.from_plan(pool)
        assert (radix.low_watermark, radix.high_watermark) == \
            (wm["low"], wm["high"])
        # full-depth stamp of the 2-layer target: draft == target, so
        # greedy proposals always verify (the machinery gate — a
        # production draft is shallower and merely accepts less)
        spec = SpeculativeDecoder(stamp_draft(m, num_layers=2), k=3)

        pa = rng.randint(2, 48, (9,)).astype(np.int64)
        pb = rng.randint(2, 48, (9,)).astype(np.int64)
        # target-only references through the PLAIN paged engine (itself
        # token-equal to generate(), gated by page_smoke) — it compiles
        # the same prefill/decode buckets the spec engine reuses, so
        # the whole gate pays the XLA toll once
        ref_pool = PagedKVPool.from_plan(plan)
        ref_eng = ContinuousBatchingEngine(m, max_slots=2,
                                           kv_pool=ref_pool).start()
        try:
            refs = {key: np.asarray(
                        ref_eng.submit(p, max_length=6).result(timeout=60))
                    for key, p in (("a", pa), ("b", pb))}
        finally:
            ref_eng.stop()
        ref_pool.assert_drained()

        eng = ContinuousBatchingEngine(m, max_slots=2, kv_pool=pool,
                                       prefix_cache=radix,
                                       speculative=spec).start()
        try:
            # -- warmup: cold prefill + radix-hit reuse shapes ---------
            out = eng.submit(pa, max_length=6).result(timeout=60)
            np.testing.assert_array_equal(out, refs["a"])
            out = eng.submit(pa, max_length=6).result(timeout=60)
            np.testing.assert_array_equal(out, refs["a"])
            warm_buckets = eng.kv_buckets

            # -- radix hit skips prefill compute -----------------------
            pre_prefill = metrics.counter("gen.prefill_tokens")
            pre_hit = metrics.counter("kv.radix_hit_tokens")
            pre_steps = metrics.counter("spec.steps")
            pre_tokens = metrics.counter("gen.tokens")
            out = eng.submit(pa, max_length=6).result(timeout=60)
            np.testing.assert_array_equal(out, refs["a"])
            prefill_ran = metrics.counter("gen.prefill_tokens") - pre_prefill
            hit_tokens = metrics.counter("kv.radix_hit_tokens") - pre_hit
            assert hit_tokens > 0, "second identical prompt missed the " \
                "radix tree"
            assert prefill_ran == pa.size - hit_tokens, \
                f"prefill ran {prefill_ran} tokens, expected only the " \
                f"{pa.size - hit_tokens}-token uncovered suffix"
            assert prefill_ran < pa.size, "radix hit skipped no compute"

            # -- speculative: > 1 committed token per target step ------
            spec_steps = metrics.counter("spec.steps") - pre_steps
            committed = metrics.counter("gen.tokens") - pre_tokens
            accepted_per_step = committed / max(1, spec_steps)
            assert accepted_per_step > 1.0, \
                f"speculation bought nothing: {committed} tokens over " \
                f"{spec_steps} verify steps"

            # -- cold second prompt: no new compiled shapes ------------
            out = eng.submit(pb, max_length=6).result(timeout=60)
            np.testing.assert_array_equal(out, refs["b"])
            buckets_after = eng.kv_buckets
        finally:
            eng.stop()
        retraces = buckets_after - warm_buckets
        assert retraces == 0, \
            f"{retraces} new compiled KV buckets after warmup — radix " \
            f"reuse or spec verify leaked shapes"
        retained = pool.pages_retained
        assert retained > 0, "retirement inserted nothing into the tree"
        pool.assert_drained()    # retained pages are clean, not leaks
        radix.clear()
        assert pool.pages_retained == 0
        pool.assert_drained()

    wall = time.time() - t0
    result = {
        "metric": "spec_smoke_wall_s",
        "value": round(wall, 2),
        "unit": "s",
        "pages": plan["pages"],
        "watermarks": [wm["low"], wm["high"]],
        "draft_kv_bytes": plan["draft_kv_bytes"],
        "radix_hit_tokens": int(hit_tokens),
        "prefill_tokens_on_hit": int(prefill_ran),
        "prompt_tokens": int(pa.size),
        "accepted_per_step": round(accepted_per_step, 2),
        "retained_pages_at_drain": int(retained),
        "traces_after_warmup": retraces,
    }
    return result


def main():
    result = run_smoke()
    print(json.dumps(result))


if __name__ == "__main__":
    main()

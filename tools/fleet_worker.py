"""Fleet trainer worker — one host of a simulated multi-host elastic fleet.

Spawned by ``paddle_tpu.distributed.launch --elastic --fleet_dir ...``
(tools/fleet_smoke.py drives two of these as two "hosts").  Reads the
``PADDLE_TPU_FLEET_*`` env contract, builds the elasticized toy model
(logical_dp=8), resumes from the SHARED checkpoint root at the fleet's
agreed restore step — a rank-merged load when the writer world differs
— and trains the remaining global steps on its local mesh, publishing
multi-host checkpoints through the fleet barrier (save → wait → barrier
→ rank-0 commit).  ``PADDLE_TPU_CHAOS`` ``lose_host@...`` may take this
whole host (launcher included) down mid-run — that is the point.

Each incarnation incrementally rewrites
``$PADDLE_TPU_FLEET_TEST_DIR/out_host<h>_e<epoch>.json`` with its loss
trace so the smoke can stitch the survivor's story even for killed
incarnations; the completing incarnation adds final params + done=True.
"""
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LOGICAL = 8

if "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") +
        f" --xla_force_host_platform_device_count={LOGICAL}").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def build_elastic():
    import paddle_tpu.static as static
    from paddle_tpu.static import layers
    from paddle_tpu.core.program import _reset_unique_names
    from paddle_tpu.distributed.elastic import elasticize
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        h = layers.fc(x, 16, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.Adam(learning_rate=1e-2).minimize(loss)
    meta = elasticize(main, startup, logical_dp=LOGICAL, loss_name=loss)
    return main, startup, loss, meta


def feeds_for(total_steps):
    rng = np.random.RandomState(0)
    return [{"x": rng.rand(LOGICAL, 8).astype(np.float32),
             "y": rng.rand(LOGICAL, 1).astype(np.float32)}
            for _ in range(total_steps)]


def main():
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.static as static
    from paddle_tpu.checkpoint import CheckpointManager
    from paddle_tpu.distributed.compiled_program import CompiledProgram
    from paddle_tpu.distributed.elastic import rebucket_feeds
    from paddle_tpu.distributed.fleet_control import fleet_env

    fl = fleet_env()
    assert fl is not None, "fleet_worker needs the PADDLE_TPU_FLEET_* env"
    base = os.environ["PADDLE_TPU_FLEET_TEST_DIR"]
    total = int(os.environ.get("FLEET_TOTAL_STEPS", "4"))
    # this host's local mesh: its even share of the fleet world
    world = max(1, fl.world // fl.n_hosts)
    k = LOGICAL // world
    out_json = os.path.join(base, f"out_host{fl.host}_e{fl.epoch}.json")

    main_, startup, loss, meta = build_elastic()
    exe = static.Executor()
    scope = static.Scope()
    mgr = CheckpointManager(os.path.join(base, "ckpt"), rank=fl.rank,
                            world_size=fl.n_hosts)
    mgr.install_preemption_handler()  # SIGTERM -> final staged snapshot
    barrier = fl.barrier(timeout_s=120.0) if fl.n_hosts > 1 else None

    losses = {}
    g = 0

    def report(done=False, params=None):
        rec = {"host": fl.host, "epoch": fl.epoch, "rank": fl.rank,
               "hosts": fl.hosts, "fleet_world": fl.world, "world": world,
               "restore_step_env": fl.restore_step, "resumed_global": g,
               "losses": losses, "done": done}
        if params is not None:
            rec["params"] = params
        tmp = out_json + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, out_json)

    with static.scope_guard(scope):
        exe.run(startup)
        exe.enable_checkpointing(mgr, program=main_, every_n_steps=k,
                                 scope=scope, barrier=barrier)
        resumed = exe.restore_from_checkpoint(
            mgr, program=main_, scope=scope, world=world,
            step=fl.restore_step)
        if resumed is not None:
            g = int(exe.last_restored_extra.get("global_step", 0))
        report()
        cp = CompiledProgram(main_).with_data_parallel(
            loss_name=loss.name, places=list(jax.devices())[:world])
        for gi, f in enumerate(feeds_for(total)[g:], start=g):
            for mf in rebucket_feeds(f, LOGICAL, world):
                out = exe.run(cp, feed=mf, fetch_list=[meta["loss_avg"]])
            losses[gi] = float(np.asarray(out[0]).reshape(-1)[0])
            report()
        params = {p.name: np.asarray(scope.get(p.name)).tolist()
                  for p in main_.all_parameters()}
        report(done=True, params=params)
    mgr.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Diff the live op registry against every REGISTER_OPERATOR name in the
reference tree — the scripted coverage check the round-4 verdict ran by
hand; landing it here keeps the residue at zero.

Usage: python tools/registry_diff.py [--ref /root/reference] [--all]

Prints the reference forward-op names with no same-name registration,
split into (a) real gaps and (b) names descoped by documented redesign
(CUDA/cuDNN/MKLDNN-only fusions, TensorRT/Lite bridges, reader plumbing
 — each class listed with its reason).  Exit code 1 if real gaps remain.
"""
import argparse
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# name-pattern classes that are descoped BY DESIGN, with the argument
DESCOPED = {
    r"^fusion_|^fused_": "CUDA/MKLDNN kernel fusions — XLA fuses these "
                         "automatically inside the whole-block jit",
    r"^tensorrt_|^lite_": "TensorRT/Lite engine bridges (GPU-specific "
                          "inference runtimes)",
    r"nccl": "NCCL plumbing — XLA collectives over ICI own this "
             "(ops/kernels/collective.py)",
    r"^create_.*reader$|^read$|^read_from_array$|^write_to_array$":
        "C++ reader op stack — the Python DataLoader/Dataset path "
        "(io/dataloader.py, distributed/dataset.py) is the redesign",
    r"^dequeue$|^enqueue$|^queue_generator$":
        "implemented over KV named queues (distributed_ops.py)",
    r"^gen_nccl_id$|^c_gen_nccl_id$|^c_comm_init":
        "jax.distributed bootstrap replaces NCCL id exchange",
    r"^(ref_by_trainer_id|split_byref|split_ids|prefetch|checkpoint_notify"
    r"|fl_listen_and_serv|distributed_notify|gen_bkcl_id|c_wait_comm"
    r"|c_wait_compute)$":
        "BRPC/fleet-DES wire details below the KV-server redesign "
        "(distributed/ps/kv_server.py provides the capability)",
    r"mkldnn|cudnn": "backend-specific kernel variants",
    r"^conv2d_fusion$|^conv2d_inception_fusion$":
        "cuDNN-only conv+bias+act fusion entry points — XLA fuses "
        "conv+bias+activation automatically in the whole-block jit",
    r"^anchor_generator$|^collect_fpn_proposals$|^distribute_fpn_proposals$"
    r"|^generate_mask_labels$|^generate_proposal_labels$"
    r"|^generate_proposals$|^retinanet_":
        "two-stage detection trainer internals (descoped: SURVEY lists "
        "SSD/YOLO tier as the detection surface; these are listed so the "
        "gap is explicit, not hidden)",
}


def reference_forward_ops(ref_root):
    """Every REGISTER_OPERATOR / REGISTER_OP_WITHOUT_GRADIENT first-arg
    name in the reference operators tree (forward ops only: *_grad
    registrations are derived here)."""
    pat = re.compile(
        r"REGISTER_OP(?:ERATOR|_WITHOUT_GRADIENT|_CPU_KERNEL)?\s*\(\s*"
        r"([a-zA-Z0-9_]+)\s*,", re.S)
    names = set()
    opdir = os.path.join(ref_root, "paddle/fluid/operators")
    for dirpath, _, files in os.walk(opdir):
        for f in files:
            if not f.endswith(".cc"):
                continue
            try:
                text = open(os.path.join(dirpath, f), errors="ignore").read()
            except OSError:
                continue
            for m in pat.finditer(text):
                n = m.group(1)
                if not n.endswith("_grad") and not n.endswith("_grad2"):
                    names.add(n)
    return names


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ref", default="/root/reference")
    ap.add_argument("--all", action="store_true",
                    help="also list descoped names per class")
    args = ap.parse_args()

    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu  # noqa: F401  (registers all kernels)
    from paddle_tpu.ops.registry import _REGISTRY
    ours = set(_REGISTRY)

    ref = reference_forward_ops(args.ref)
    missing = sorted(ref - ours)
    gaps, descoped = [], {}
    for n in missing:
        for pat, why in DESCOPED.items():
            if re.search(pat, n):
                descoped.setdefault(why, []).append(n)
                break
        else:
            gaps.append(n)

    print(f"reference forward ops: {len(ref)}")
    print(f"registered here:       {len(ours)} "
          f"({len(ref & ours)} exact-name matches)")
    print(f"descoped by design:    "
          f"{sum(len(v) for v in descoped.values())}")
    if args.all:
        for why, names in sorted(descoped.items()):
            print(f"  [{len(names)}] {why}")
            for n in names:
                print(f"      {n}")
    print(f"REAL GAPS:             {len(gaps)}")
    for n in gaps:
        print(f"  {n}")
    return 1 if gaps else 0


if __name__ == "__main__":
    sys.exit(main())
